"""Bring up the serving STACK (engine API server + router) as subprocesses.

Used by bench.py and the e2e tests so the recorded benchmark exercises the
same deployment shape the reference measures: client -> router (session
routing, SSE relay) -> engine pod (reference tutorials/
07-benchmark-multi-round-qa-single-gpu.md procedure).
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from dataclasses import dataclass, field
from typing import List, Optional


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_tcp(host: str, port: int, timeout_s: float, proc: subprocess.Popen,
             name: str) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"{name} exited with code {proc.returncode} before listening"
            )
        try:
            socket.create_connection((host, port), 0.5).close()
            return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"{name} not listening on {host}:{port} "
                       f"after {timeout_s}s")


@dataclass
class KVServerHandle:
    """Restartable cache-server subprocess (soak chaos: restart_kv_server).
    The port is pinned so LMCACHE_REMOTE_URL stays valid across restarts —
    engines reconnect via RemoteKVClient's one-shot retry."""

    proc: subprocess.Popen
    url: str
    port: int
    log_path: str
    log_file: object
    max_bytes: int

    def _spawn(self) -> subprocess.Popen:
        return subprocess.Popen(
            [
                sys.executable, "-m",
                "production_stack_tpu.kv_offload.server",
                "--force-python", "--host", "127.0.0.1",
                "--port", str(self.port), "--max-bytes", str(self.max_bytes),
            ],
            stdout=self.log_file, stderr=subprocess.STDOUT,
        )

    def restart(self, timeout_s: float = 60.0) -> float:
        """SIGTERM -> wait exit -> relaunch on the SAME port -> wait
        listening. Returns the downtime in seconds."""
        t0 = time.monotonic()
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=15)
        self.proc = self._spawn()
        wait_tcp("127.0.0.1", self.port, timeout_s, self.proc, "kv_server")
        return time.monotonic() - t0

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.log_file.close()


def launch_kv_server(max_bytes: int = 1 << 30, log_dir: str = "/tmp"):
    """Start the Python cache server as a subprocess; returns
    (Popen, kv_url, log_path, log_file) — see also launch_kv_server_handle
    for the restartable wrapper the soak harness drives. The disagg bench
    mode's handoff plane and the engines' LMCACHE_REMOTE_URL both point
    at it."""
    h = launch_kv_server_handle(max_bytes=max_bytes, log_dir=log_dir)
    return h.proc, h.url, h.log_path, h.log_file


def launch_kv_server_handle(max_bytes: int = 1 << 30,
                            log_dir: str = "/tmp") -> KVServerHandle:
    port = free_port()
    log = os.path.join(log_dir, f"pstpu-bench-kvserver-{port}.log")
    log_f = open(log, "w")
    handle = KVServerHandle(
        proc=None, url=f"kv://127.0.0.1:{port}", port=port,  # type: ignore
        log_path=log, log_file=log_f, max_bytes=max_bytes,
    )
    handle.proc = handle._spawn()
    try:
        wait_tcp("127.0.0.1", port, 60.0, handle.proc, "kv_server")
    except Exception:
        handle.proc.kill()
        log_f.close()
        raise
    return handle


def wait_health(url: str, timeout_s: float, proc: subprocess.Popen,
                name: str) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"{name} exited with code {proc.returncode} before becoming "
                f"healthy (see its log output)"
            )
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                if resp.status == 200:
                    return
        except Exception:  # noqa: BLE001 — not up yet
            time.sleep(1.0)
    raise TimeoutError(f"{name} not healthy after {timeout_s}s ({url})")


@dataclass
class StackHandle:
    engines: List[subprocess.Popen]
    routers: List[subprocess.Popen]
    engine_urls: List[str]
    router_urls: List[str]
    log_paths: List[str] = field(default_factory=list)
    log_files: List[object] = field(default_factory=list)
    # Relaunch state (soak chaos: restart_engine): engine i's exact argv,
    # its log file, and the env overrides it was launched with.
    engine_cmds: List[List[str]] = field(default_factory=list)
    engine_log_files: List[object] = field(default_factory=list)
    engine_env: Optional[dict] = None
    # Elastic fast-start (docs/ELASTIC.md): per-engine process-spawn ->
    # /health-200 seconds (initial launch, relaunches overwrite their
    # slot, scale-outs append), the served model name, and — when the
    # router runs static discovery behind a dynamic-config file — the
    # file scale_out/scale_in rewrite so the router learns the new fleet.
    engine_ready_seconds: List[float] = field(default_factory=list)
    served_model: str = ""
    dynamic_config_path: Optional[str] = None
    dynamic_config_watch_interval: float = 10.0
    log_dir: str = "/tmp"

    def _write_dynamic_config(self) -> None:
        assert self.dynamic_config_path is not None
        doc = {
            "service_discovery": "static",
            "static_backends": ",".join(self.engine_urls),
            "static_models": ",".join(
                [self.served_model] * len(self.engine_urls)
            ),
        }
        tmp = self.dynamic_config_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.dynamic_config_path)  # atomic vs the watcher

    def scale_out(self, startup_timeout_s: float = 1800.0) -> dict:
        """Add one engine to the running stack (the soak's local HPA
        emulation, docs/ELASTIC.md): spawn engine 0's argv on a fresh
        port (same flags — including any shared --compilation-cache-dir,
        so the joiner takes the warm-start path), wait for /health, then
        rewrite the router's dynamic-config file so static discovery
        picks it up within the watch interval. Requires the stack to have
        been launched with dynamic_config_path."""
        if self.dynamic_config_path is None:
            raise RuntimeError(
                "scale_out requires launch_stack(dynamic_config_path=...) "
                "(the router must be watching a dynamic config file)"
            )
        port = free_port()
        url = f"http://127.0.0.1:{port}"
        cmd = list(self.engine_cmds[0])
        cmd[cmd.index("--port") + 1] = str(port)
        elog = os.path.join(self.log_dir, f"pstpu-bench-engine-{port}.log")
        elog_f = open(elog, "w")
        env = ({**os.environ, **self.engine_env}
               if self.engine_env else None)
        t0 = time.monotonic()
        proc = subprocess.Popen(
            cmd, stdout=elog_f, stderr=subprocess.STDOUT, env=env,
        )
        try:
            wait_health(f"{url}/health", startup_timeout_s, proc,
                        f"engine {url} (scale-out)")
        except Exception:
            proc.kill()
            elog_f.close()
            raise
        ready_s = time.monotonic() - t0
        self.engines.append(proc)
        self.engine_urls.append(url)
        self.engine_cmds.append(cmd)
        self.engine_log_files.append(elog_f)
        self.engine_ready_seconds.append(ready_s)
        self.log_paths.append(elog)
        self.log_files.append(elog_f)
        self._write_dynamic_config()
        return {"url": url, "index": len(self.engines) - 1,
                "engine_ready_s": round(ready_s, 3)}

    def scale_in(self, index: int = -1,
                 drain_timeout_s: float = 60.0) -> dict:
        """Remove engine ``index`` (default: the newest) with zero 5xx:
        the dynamic-config rewrite drops it from routing FIRST, the
        watch interval is waited out (plus margin) so the router stops
        picking it, then SIGTERM triggers the engine's graceful drain
        (in-flight streams finish; its hot KV is already spilled to the
        shared tier by the write-through offload path)."""
        if self.dynamic_config_path is None:
            raise RuntimeError(
                "scale_in requires launch_stack(dynamic_config_path=...)"
            )
        if index < 0:
            index = len(self.engines) + index
        if not 0 <= index < len(self.engines) or len(self.engines) <= 1:
            raise ValueError(f"cannot scale in engine {index} of "
                             f"{len(self.engines)}")
        proc = self.engines.pop(index)
        url = self.engine_urls.pop(index)
        self.engine_cmds.pop(index)
        elog_f = self.engine_log_files.pop(index)
        if index < len(self.engine_ready_seconds):
            self.engine_ready_seconds.pop(index)
        self._write_dynamic_config()
        # Let the watcher apply the shrunken fleet before the drain
        # starts, so no request is routed at a draining backend (the
        # router's retry ladder would still absorb one, but the clean
        # path is route-away-first).
        time.sleep(self.dynamic_config_watch_interval + 1.0)
        t0 = time.monotonic()
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=drain_timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=30)
        try:
            self.log_files.remove(elog_f)
        except ValueError:
            pass
        elog_f.close()
        return {"url": url, "drain_s": round(time.monotonic() - t0, 3)}

    @property
    def engine(self) -> subprocess.Popen:
        """First engine process (single-engine callers / run*.sh)."""
        return self.engines[0]

    @property
    def engine_url(self) -> str:
        return self.engine_urls[0]

    @property
    def router(self) -> subprocess.Popen:
        """First LIVE router process (single-router callers / run*.sh)."""
        for proc in self.routers:
            if proc.poll() is None:
                return proc
        return self.routers[0]

    @property
    def router_url(self) -> str:
        """URL of the first LIVE router replica. After kill_router the
        facade moves to the next survivor, so single-URL callers keep
        working through a router death (docs/ROUTER_SCALE.md)."""
        for proc, url in zip(self.routers, self.router_urls):
            if proc.poll() is None:
                return url
        raise RuntimeError("no live router replica")

    @property
    def live_router_urls(self) -> List[str]:
        """All currently-live router replica URLs (metrics-merge scrapes)."""
        return [url for proc, url in zip(self.routers, self.router_urls)
                if proc.poll() is None]

    def kill_router(self, index: int) -> float:
        """HARD-kill router replica ``index``: SIGKILL, no drain, no
        relaunch — in-flight client streams die mid-byte and the client
        must reconnect to a surviving replica with its
        x-pstpu-resume-* state (docs/ROUTER_SCALE.md). Returns seconds
        spent waiting for the process to die."""
        if len(self.live_router_urls) <= 1:
            raise RuntimeError(
                "refusing to kill the last live router replica"
            )
        proc = self.routers[index]
        t0 = time.monotonic()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)
        return time.monotonic() - t0

    def _relaunch_engine(self, index: int, startup_timeout_s: float) -> None:
        """Relaunch engine ``index``'s exact argv/env on the same port and
        block until /health is 200 again."""
        env = ({**os.environ, **self.engine_env}
               if self.engine_env else None)
        t0 = time.monotonic()
        new = subprocess.Popen(
            self.engine_cmds[index],
            stdout=self.engine_log_files[index], stderr=subprocess.STDOUT,
            env=env,
        )
        self.engines[index] = new
        wait_health(f"{self.engine_urls[index]}/health", startup_timeout_s,
                    new, f"engine {self.engine_urls[index]} (restarted)")
        # The relaunch reuses the same argv (incl. any shared
        # --compilation-cache-dir), so this measures the WARM-start path
        # the chaos-recovery bars benefit from (docs/ELASTIC.md).
        if index < len(self.engine_ready_seconds):
            self.engine_ready_seconds[index] = time.monotonic() - t0
        else:
            self.engine_ready_seconds.append(time.monotonic() - t0)

    def restart_engine(self, index: int, startup_timeout_s: float = 1800.0,
                       kill_timeout_s: float = 60.0) -> float:
        """Rolling-restart engine ``index``: SIGTERM (graceful drain — the
        engine finishes in-flight streams, sheds new work with
        503+Retry-After, then exits), wait for exit, relaunch the same
        argv/env on the same port, block until /health is 200 again.
        Returns the measured downtime in seconds. Blocking by design: the
        soak harness calls it via asyncio.to_thread so traffic keeps
        flowing while the pod bounces."""
        proc = self.engines[index]
        t0 = time.monotonic()
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=kill_timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=kill_timeout_s)
        self._relaunch_engine(index, startup_timeout_s)
        return time.monotonic() - t0

    def kill_engine(self, index: int, startup_timeout_s: float = 1800.0,
                    relaunch: bool = True) -> float:
        """HARD-kill engine ``index``: SIGKILL, no drain — in-flight SSE
        streams die mid-byte, exactly the fault the router's mid-stream
        resume exists for (docs/RESILIENCE.md). Then (by default) relaunch
        on the same port like restart_engine. Returns the downtime."""
        proc = self.engines[index]
        t0 = time.monotonic()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)
        if relaunch:
            self._relaunch_engine(index, startup_timeout_s)
        return time.monotonic() - t0

    def terminate(self) -> None:
        procs = [*self.routers, *self.engines]
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=15)
        for f in self.log_files:
            f.close()
        self.log_files.clear()


def launch_stack(
    model: str,
    *,
    engine_args: Optional[List[str]] = None,
    router_args: Optional[List[str]] = None,
    routing_logic: str = "session",
    served_model: Optional[str] = None,
    startup_timeout_s: float = 1800.0,
    log_dir: str = "/tmp",
    num_engines: int = 1,
    num_routers: int = 1,
    per_engine_args: Optional[List[List[str]]] = None,
    engine_env: Optional[dict] = None,
    tensor_parallel_size: int = 1,
    compilation_cache_dir: Optional[str] = None,
    dynamic_config_path: Optional[str] = None,
    dynamic_config_watch_interval: float = 1.0,
) -> StackHandle:
    """Start ``num_engines`` engine pods + the router; block until all are
    healthy. Multiple engines make the load-balancing routing logics
    (e.g. cache_aware_load_balancing) actually route — the 2-process
    opt-125m smoke path in the benchmark sweep. ``per_engine_args[i]`` are
    appended to engine i's argv (role-split disagg pools) and
    ``engine_env`` entries override the inherited environment (e.g.
    LMCACHE_REMOTE_URL for the shared offload store).

    ``tensor_parallel_size`` > 1 boots every engine on a tp-sharded device
    mesh (threaded through per_engine_args, so a caller's own per-engine
    extras can still override it per pod). On CPU the caller must also put
    ``--xla_force_host_platform_device_count=N`` into the subprocesses'
    XLA_FLAGS (bench.py does; the same code path IS the TPU slice path,
    where the real devices are just present).

    Elastic fast-start (docs/ELASTIC.md): ``compilation_cache_dir``
    threads ``--compilation-cache-dir`` into every engine subprocess
    (restarts and scale-outs reuse the argv, so relaunches boot warm);
    ``dynamic_config_path`` makes the router watch a dynamic-config file
    seeded with the initial fleet, enabling StackHandle.scale_out /
    scale_in mid-run; per-engine spawn->/health seconds land in
    StackHandle.engine_ready_seconds (healths are awaited sequentially,
    so later engines' values include queue wait — use a 1-engine stack
    for a clean cold/warm boot A/B).

    ``num_routers`` > 1 boots a horizontally-scaled router tier
    (docs/ROUTER_SCALE.md): every replica sees the same backend set,
    carries ``--router-id router-<i>``, and shares a
    ``--router-peer-dir`` under ``log_dir`` for breaker gossip. Clients
    spread across StackHandle.router_urls; StackHandle.kill_router is
    the matching chaos fault."""
    if tensor_parallel_size > 1:
        pea = [list(a) for a in (per_engine_args or [])]
        while len(pea) < max(1, num_engines):
            pea.append([])
        per_engine_args = [
            ["--tensor-parallel-size", str(tensor_parallel_size), *a]
            for a in pea
        ]
    num_routers = max(1, num_routers)
    router_ports = [free_port() for _ in range(num_routers)]
    router_urls = [f"http://127.0.0.1:{p}" for p in router_ports]
    served = served_model or model

    engines: List[subprocess.Popen] = []
    engine_urls: List[str] = []
    engine_cmds: List[List[str]] = []
    engine_log_files: List[object] = []
    engine_spawn_times: List[float] = []
    engine_ready_seconds: List[float] = []
    log_paths: List[str] = []
    log_files: List[object] = []
    rlog_f = None
    try:
        for i in range(max(1, num_engines)):
            engine_port = free_port()
            engine_url = f"http://127.0.0.1:{engine_port}"
            elog = os.path.join(
                log_dir, f"pstpu-bench-engine-{engine_port}.log"
            )
            elog_f = open(elog, "w")
            log_paths.append(elog)
            log_files.append(elog_f)
            extra = (
                per_engine_args[i]
                if per_engine_args and i < len(per_engine_args) else []
            )
            cmd = [
                sys.executable, "-m",
                "production_stack_tpu.server.api_server",
                "--model", model, "--port", str(engine_port),
                *(["--compilation-cache-dir", compilation_cache_dir]
                  if compilation_cache_dir is not None else []),
                *(engine_args or []),
                *extra,
            ]
            engine_spawn_times.append(time.monotonic())
            engines.append(subprocess.Popen(
                cmd,
                stdout=elog_f, stderr=subprocess.STDOUT,
                env=({**os.environ, **engine_env} if engine_env else None),
            ))
            engine_urls.append(engine_url)
            engine_cmds.append(cmd)
            engine_log_files.append(elog_f)
        for engine, engine_url, spawn_t in zip(engines, engine_urls,
                                               engine_spawn_times):
            wait_health(f"{engine_url}/health", startup_timeout_s, engine,
                        f"engine {engine_url}")
            engine_ready_seconds.append(time.monotonic() - spawn_t)
        dyn_args: List[str] = []
        if dynamic_config_path is not None:
            with open(dynamic_config_path, "w") as f:
                json.dump({
                    "service_discovery": "static",
                    "static_backends": ",".join(engine_urls),
                    "static_models": ",".join([served] * len(engine_urls)),
                }, f)
            dyn_args = [
                "--dynamic-config-json", dynamic_config_path,
                "--dynamic-config-watch-interval",
                str(dynamic_config_watch_interval),
            ]
        peer_args: List[str] = []
        if num_routers > 1:
            # Shared breaker-gossip directory for the replica tier. The
            # gossip rides the dynamic-config watcher thread, so pin its
            # interval even when no config file is watched.
            peer_dir = os.path.join(
                log_dir, f"pstpu-router-peers-{router_ports[0]}"
            )
            os.makedirs(peer_dir, exist_ok=True)
            peer_args = ["--router-peer-dir", peer_dir]
            if not dyn_args:
                peer_args += ["--dynamic-config-watch-interval",
                              str(dynamic_config_watch_interval)]
        routers: List[subprocess.Popen] = []
        for i, rport in enumerate(router_ports):
            router_cmd = [
                sys.executable, "-m", "production_stack_tpu.router.app",
                "--port", str(rport),
                "--service-discovery", "static",
                "--static-backends", ",".join(engine_urls),
                "--static-models", ",".join([served] * len(engine_urls)),
                "--routing-logic", routing_logic,
                "--router-id", f"router-{i}",
                *peer_args,
                *dyn_args,
                *(router_args or []),
            ]
            rlog = os.path.join(log_dir, f"pstpu-bench-router-{rport}.log")
            rlog_f = open(rlog, "w")
            log_paths.append(rlog)
            log_files.append(rlog_f)
            routers.append(subprocess.Popen(
                router_cmd, stdout=rlog_f, stderr=subprocess.STDOUT,
            ))
        try:
            for r, rurl in zip(routers, router_urls):
                wait_health(f"{rurl}/health", 120.0, r, f"router {rurl}")
        except Exception:
            for r in routers:
                r.kill()
            raise
    except Exception:
        for engine in engines:
            engine.kill()
        for f in log_files:
            f.close()
        raise
    return StackHandle(
        engines=engines, routers=routers, engine_urls=engine_urls,
        router_urls=router_urls, log_paths=log_paths, log_files=log_files,
        engine_cmds=engine_cmds, engine_log_files=engine_log_files,
        engine_env=dict(engine_env) if engine_env else None,
        engine_ready_seconds=engine_ready_seconds,
        served_model=served,
        dynamic_config_path=dynamic_config_path,
        dynamic_config_watch_interval=dynamic_config_watch_interval,
        log_dir=log_dir,
    )
