#!/bin/bash
# QPS-sweep benchmark procedure (reference benchmarks/multi-round-qa/run.sh).
#
# Usage: ./run.sh <model> <base url> <save file key> [launch]
#   model          served model name (e.g. llama-1b)
#   base url       router URL (e.g. http://localhost:30080)
#   save file key  output prefix: {key}_output_{qps}.csv per QPS point
#   launch         pass "launch" to bring up an engine+router stack locally
#                  first (benchmarks/stack.py) and sweep against it
#
# Afterwards: python3 benchmarks/plot.py to draw the TTFT-vs-QPS curve.
set -e

if [[ $# -lt 3 ]]; then
    echo "Usage: $0 <model> <base url> <save file key> [launch]"
    exit 1
fi

MODEL=$1
BASE_URL=$2
KEY=$3
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO_ROOT"

if [[ "${4:-}" == "launch" ]]; then
    eval "$(python3 - "$MODEL" <<'EOF'
import sys
from benchmarks.stack import launch_stack
stack = launch_stack(sys.argv[1], routing_logic="session",
                     router_args=["--session-key", "x-user-id"])
print(f"BASE_URL={stack.router_url}")
print(f"STACK_PIDS='{stack.engine.pid} {stack.router.pid}'")
EOF
)"
    trap 'kill $STACK_PIDS 2>/dev/null || true' EXIT
    echo "Launched stack at $BASE_URL"
fi

# Workload shape (reference run.sh CONFIGURATION block; answer/system sizes
# identical, users scaled to a single-host sweep — override via env).
NUM_USERS=${NUM_USERS:-320}
NUM_ROUNDS=${NUM_ROUNDS:-10}
SYSTEM_PROMPT_WORDS=${SYSTEM_PROMPT_WORDS:-150}   # ~1000 tok system prompt
ANSWER_LEN=${ANSWER_LEN:-100}
TIME_LIMIT=${TIME_LIMIT:-100}
NUM_USERS_WARMUP=${NUM_USERS_WARMUP:-400}

warmup() {
    python3 -m benchmarks.multi_round_qa \
        --num-users 1 \
        --num-rounds 2 \
        --qps 2 \
        --system-prompt-words "$SYSTEM_PROMPT_WORDS" \
        --answer-tokens "$ANSWER_LEN" \
        --model "$MODEL" \
        --base-url "$BASE_URL" \
        --output /tmp/warmup.csv \
        --time $((NUM_USERS_WARMUP / 2))
}

run_benchmark() {
    # $1: qps   $2: output file
    python3 -m benchmarks.multi_round_qa \
        --num-users "$NUM_USERS" \
        --num-rounds "$NUM_ROUNDS" \
        --qps "$1" \
        --system-prompt-words "$SYSTEM_PROMPT_WORDS" \
        --answer-tokens "$ANSWER_LEN" \
        --model "$MODEL" \
        --base-url "$BASE_URL" \
        --output "$2" \
        --time "$TIME_LIMIT"
    sleep 10
}

warmup

# Reference sweep order: ascending for the naive baseline, descending
# otherwise (prefix caches warm at high load first).
if [[ "$KEY" == "naive" ]]; then
    QPS_VALUES=(0.1 0.5 0.9 1.3 1.7 2.1 2.5 2.9 3.3 3.7 4.1)
else
    QPS_VALUES=(4.1 3.7 3.3 2.9 2.5 2.1 1.7 1.3 0.9 0.5 0.1)
fi

for qps in "${QPS_VALUES[@]}"; do
    output_file="${KEY}_output_${qps}.csv"
    run_benchmark "$qps" "$output_file"
done
