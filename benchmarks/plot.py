"""TTFT-vs-QPS sweep curve from run.sh output CSVs.

Mirrors reference benchmarks/multi-round-qa/plot.py: for each key
(deployment variant) read {key}_output_{qps}.csv, average the 'ttft'
column, and draw one line per key. Keys are discovered from the files
present, so any set of variants plots (the reference hard-codes
stack/aibrix/naive).

Usage:
    python3 benchmarks/plot.py [--dir .] [--out multi-round.png]
"""

import argparse
import glob
import os
import re

import pandas as pd

QPS_RANGE = [0.1, 0.5, 0.9, 1.3, 1.7, 2.1, 2.5, 2.9, 3.3, 3.7, 4.1]
_STYLE = {
    "stack": {"marker": "x", "color": "blue"},
    "aibrix": {"marker": "o", "color": "red"},
    "naive": {"marker": "s", "color": "green"},
}


def collect(directory: str):
    """{key: (qps_list, avg_ttft_list)} from {key}_output_{qps}.csv files."""
    keys = sorted({
        m.group(1)
        for f in glob.glob(os.path.join(directory, "*_output_*.csv"))
        if (m := re.match(r"(.+)_output_[\d.]+\.csv$", os.path.basename(f)))
    })
    out = {}
    for key in keys:
        qpses, ttfts = [], []
        for qps in QPS_RANGE:
            f = os.path.join(directory, f"{key}_output_{round(qps, 1)}.csv")
            if not os.path.exists(f):
                continue
            data = pd.read_csv(f)["ttft"].tolist()
            if not data:
                continue
            qpses.append(round(qps, 1))
            ttfts.append(sum(data) / len(data))
        if qpses:
            out[key] = (qpses, ttfts)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default=".")
    ap.add_argument("--out", default="multi-round.png")
    args = ap.parse_args()

    import matplotlib

    matplotlib.use("Agg")
    from matplotlib import pyplot as plt

    curves = collect(args.dir)
    if not curves:
        raise SystemExit(f"no *_output_*.csv files under {args.dir!r} — "
                         f"run benchmarks/run.sh first")
    for key, (qpses, ttfts) in curves.items():
        print(f"{key} avg TTFT", ttfts)
        plt.plot(qpses, ttfts, label=key, linewidth=2, markersize=8,
                 **_STYLE.get(key, {"marker": "^"}))
    plt.xlabel("QPS")
    plt.ylabel("Average TTFT (s)")
    plt.legend()
    plt.grid(True, alpha=0.3)
    plt.tight_layout()
    plt.savefig(args.out, dpi=150)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
