"""Multi-round QA load harness over HTTP — the stack-level benchmark.

Drives an OpenAI-compatible endpoint (normally the ROUTER, so the full
router -> engine -> SSE-relay path is measured) with N concurrent user
sessions: a shared system prompt, per-user growing chat history, streaming
chat completions with the session-affinity header, TTFT measured at the
first content chunk, token counts taken from the final usage chunk
(``stream_options.include_usage``).

Metric definitions mirror the reference harness
(reference benchmarks/multi-round-qa/multi-round-qa.py:117-177 request
execution, :435-512 ProcessSummary): QPS, processing speed (finished
requests/s), input tokens/s, output tokens/s, per-request generation speed,
average + p50 TTFT. The implementation is independent (asyncio + aiohttp,
no pandas/openai-client dependency).

CLI:
    python -m benchmarks.multi_round_qa --base-url http://localhost:8000 \
        --model llama-1b --num-users 16 --num-rounds 4 --answer-tokens 64
"""

import argparse
import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import List, Optional

import aiohttp

_WORDS = (
    "the quick brown fox jumps over a lazy dog while curious engineers "
    "measure throughput latency and cache behavior of serving stacks"
).split()


def synth_text(num_words: int, seed: int = 0) -> str:
    """Deterministic filler text of ~num_words words."""
    n = len(_WORDS)
    return " ".join(_WORDS[(seed + i) % n] for i in range(max(1, num_words)))


@dataclass
class WorkloadConfig:
    base_url: str = "http://localhost:8000"
    model: str = "llama-1b"
    num_users: int = 16
    num_rounds: int = 4
    system_prompt_words: int = 120
    question_words: int = 12
    answer_tokens: int = 64
    # Pre-seeded per-user chat history (words of alternating user/assistant
    # turns prepended before round 0): the reference workload's users carry
    # LONG histories (~20k tokens), which is what makes KV/prefix-cache hit
    # rate a first-class metric — without it every round is a short fresh
    # prompt and the kv_hit_rate target is unmeasured. Deterministic per
    # user; 0 disables.
    history_words: int = 0
    gap_between_users_s: float = 0.0
    session_header: str = "x-user-id"
    api_key: Optional[str] = None
    timeout_s: float = 300.0
    # QPS-paced session ramp (reference run.sh sweep contract,
    # reference benchmarks/multi-round-qa/run.sh:43-82): a new user session
    # starts every 1/qps seconds. Overrides gap_between_users_s when set.
    qps: Optional[float] = None
    # Wall-clock bound (reference --time): sessions start no NEW round
    # after this many seconds; in-flight rounds complete and are recorded.
    time_limit_s: Optional[float] = None
    # Pre-processed ShareGPT conversations (data_preprocessing.py output):
    # questions come from real human turns instead of synthetic text.
    sharegpt: Optional[list] = None
    # Distinguishes question text across workload invocations: a warmup pass
    # must use a different tag than the timed pass so only the
    # (intentionally) shared system prefix is warm in the engine's prefix
    # cache, not the full prompts.
    tag: str = "round"
    # Extra headers on every request (soak SLO classes ride x-slo-class /
    # x-slo-ttft / x-ttft-deadline through here).
    extra_headers: Optional[dict] = None
    # 503 + Retry-After is intentional shedding, not a failure: back off
    # for the advertised interval and retry, up to max_shed_retries per
    # round. The retries are counted on the record (``sheds``) so load
    # reports can separate shed from error.
    honor_retry_after: bool = True
    max_shed_retries: int = 5
    # True (default): any terminal non-2xx status raises, the historical
    # bench contract. False (soak): the outcome is recorded on the
    # RequestRecord (status, transport errors as status 599) and the
    # workload keeps going — the soak report does the accounting.
    raise_on_error: bool = True
    # Label stamped on every record (soak per-class attribution).
    slo_class: str = ""
    # Router replica tier (docs/ROUTER_SCALE.md): when set, sessions
    # spread round-robin across these URLs (user_id % len) and a session
    # whose router dies MID-STREAM reconnects to the next replica
    # carrying x-pstpu-resume-tokens / x-pstpu-resume-seed (the pstpu
    # payload it already received) — the peer splices a token-identical
    # continuation, so a router SIGKILL is a failover, not a truncation.
    # Pre-stream connect errors rotate replicas the same way.
    base_urls: Optional[List[str]] = None
    max_router_failovers: int = 3


@dataclass
class RequestRecord:
    user: int
    round: int
    launch_time: float
    ttft: float
    finish_time: float
    prompt_tokens: int
    generation_tokens: int
    status: int = 200          # terminal HTTP status (599 = transport error)
    sheds: int = 0             # 503+Retry-After backoff-and-retry rounds
    retry_after: bool = False  # terminal 503 carried Retry-After (shed, not
                               # error — docs/SOAK.md accounting)
    slo_class: str = ""
    # A 200 SSE stream that ended WITHOUT data:[DONE]: the client kept its
    # status but lost the tail of the answer (status is forced to 599 so
    # the zero-5xx gate sees it too; this flag feeds the explicit
    # zero-truncation gate, docs/RESILIENCE.md).
    truncated: bool = False
    # The router-echoed x-request-id: the handle the soak's anomaly dump
    # uses to pull this request's flight-recorder timeline from the
    # engines (GET /debug/requests/{id}, docs/OBSERVABILITY.md).
    request_id: str = ""
    # Cross-router reconnects this round survived (docs/ROUTER_SCALE.md).
    router_failovers: int = 0

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300

    @property
    def generation_time(self) -> float:
        return max(self.finish_time - self.launch_time - self.ttft, 1e-9)

    @property
    def itl(self) -> Optional[float]:
        """Mean inter-token latency after the first token, seconds."""
        if self.generation_tokens <= 1:
            return None
        return self.generation_time / (self.generation_tokens - 1)


class UserSession:
    """One user: shared system prompt + growing per-user history, one
    streaming request per round through the session-affinity header."""

    def __init__(self, cfg: WorkloadConfig, user_id: int, system_prompt: str):
        self.cfg = cfg
        self.user_id = user_id
        self.messages = [{"role": "system", "content": system_prompt}]
        # Long-chat-history seeding: alternating user/assistant turns of
        # deterministic filler, per-user distinct (so only the system
        # prompt is cross-user shareable, while a user's OWN history is a
        # per-session prefix-cache hit on every later round).
        turn_words = 120
        seeded = 0
        turn = 0
        while seeded < cfg.history_words:
            self.messages.append({
                "role": "user",
                # cfg.tag in the history text keeps a warmup pass's
                # histories distinct from the timed pass's, so the timed
                # round 0 pays its history prefill for real and only the
                # LATER rounds measure the session prefix-cache hit.
                "content": f"user {user_id} {cfg.tag} history {turn}: "
                + synth_text(turn_words, seed=user_id * 131 + 2 * turn),
            })
            self.messages.append({
                "role": "assistant",
                "content": synth_text(
                    turn_words, seed=user_id * 131 + 2 * turn + 1
                ),
            })
            seeded += 2 * turn_words
            turn += 1
        self.records: List[RequestRecord] = []
        # Router replica rotation (docs/ROUTER_SCALE.md): each session is
        # pinned to a replica round-robin; connect failures and mid-stream
        # router deaths advance to the next one.
        self._urls = list(cfg.base_urls) if cfg.base_urls \
            else [cfg.base_url]
        self._url_idx = user_id % len(self._urls)

    def _base_url(self) -> str:
        return self._urls[self._url_idx]

    def _rotate_url(self) -> bool:
        """Advance to the next replica; False when there is nowhere else
        to go (single-URL workload)."""
        if len(self._urls) <= 1:
            return False
        self._url_idx = (self._url_idx + 1) % len(self._urls)
        return True

    def _question(self, rnd: int) -> str:
        cfg = self.cfg
        if cfg.sharegpt:
            conv = cfg.sharegpt[self.user_id % len(cfg.sharegpt)]
            humans = [
                t["value"] for t in conv.get("conversations", [])
                if t.get("from") == "human"
            ]
            if rnd < len(humans):
                return f"user {self.user_id} {cfg.tag} {rnd}: {humans[rnd]}"
        return (
            f"user {self.user_id} {cfg.tag} {rnd}: "
            + synth_text(cfg.question_words, seed=self.user_id * 31 + rnd)
        )

    async def _one_round(self, http: aiohttp.ClientSession, rnd: int) -> None:
        cfg = self.cfg
        question = self._question(rnd)
        self.messages.append({"role": "user", "content": question})
        headers = {cfg.session_header: f"user-{self.user_id}"}
        if cfg.api_key:
            headers["Authorization"] = f"Bearer {cfg.api_key}"
        if cfg.extra_headers:
            headers.update(cfg.extra_headers)
        body = {
            "model": cfg.model,
            "messages": self.messages,
            "temperature": 0,
            "max_tokens": cfg.answer_tokens,
            "ignore_eos": True,
            "stream": True,
            "stream_options": {"include_usage": True},
        }
        launch = time.monotonic()
        first: Optional[float] = None
        answer = ""
        prompt_tokens = generation_tokens = 0
        status = 599               # transport error unless a response lands
        retry_after_hdr: Optional[str] = None
        sheds = 0
        truncated = False
        request_id = ""
        # Delivered-token state for cross-router resume
        # (docs/ROUTER_SCALE.md): the pstpu payload each chunk carries is
        # exactly what a surviving replica needs to splice the tail.
        toks: List[int] = []
        seed: Optional[int] = None
        failovers = 0
        while True:
            try:
                async with http.post(
                    f"{self._base_url()}/v1/chat/completions", json=body,
                    headers=headers,
                ) as resp:
                    status = resp.status
                    retry_after_hdr = resp.headers.get("Retry-After")
                    request_id = resp.headers.get("x-request-id",
                                                  request_id)
                    if (status == 503 and retry_after_hdr is not None
                            and cfg.honor_retry_after
                            and sheds < cfg.max_shed_retries):
                        # Intentional shed (queue bound / drain / breaker):
                        # back off for the advertised interval and retry —
                        # NOT an error (docs/SOAK.md accounting).
                        await resp.read()
                        sheds += 1
                        try:
                            delay = min(5.0, float(retry_after_hdr))
                        except ValueError:
                            delay = 1.0
                        await asyncio.sleep(delay)
                        continue
                    if status >= 400:
                        await resp.read()
                        if cfg.raise_on_error:
                            resp.raise_for_status()
                        break
                    saw_done = False
                    async for raw in resp.content:
                        line = raw.decode("utf-8", "replace").strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[len("data:"):].strip()
                        if payload == "[DONE]":
                            saw_done = True
                            break
                        chunk = json.loads(payload)
                        usage = chunk.get("usage")
                        if usage:
                            prompt_tokens = usage.get("prompt_tokens", 0)
                            generation_tokens = usage.get(
                                "completion_tokens", 0)
                        # Registry-pinned payload keys (PL011 checks this
                        # consumer reads toks/off/seed; docs/HTTP_PROTOCOL.md).
                        meta = chunk.get("pstpu")
                        if isinstance(meta, dict):
                            if isinstance(meta.get("seed"), int) and \
                                    not isinstance(meta["seed"], bool):
                                seed = meta["seed"]
                            ctoks = meta.get("toks") or []
                            off = meta.get("off")
                            if ctoks and isinstance(off, int):
                                if off + len(ctoks) <= len(toks):
                                    # Already delivered before a failover
                                    # hop — drop, never repeat bytes.
                                    continue
                                toks.extend(
                                    ctoks[max(0, len(toks) - off):]
                                )
                        for choice in chunk.get("choices", []):
                            delta = (choice.get("delta") or {}).get("content")
                            if delta:
                                if first is None:
                                    first = time.monotonic()
                                answer += delta
                    if not saw_done:
                        # Stream ended without the terminal sentinel: a
                        # mid-stream truncation (backend died after bytes
                        # were on the wire and no resume spliced the tail —
                        # docs/RESILIENCE.md). The client saw a broken
                        # answer, so it counts as an error, not a 200 —
                        # otherwise the soak's zero-5xx gate would be
                        # blind to hard mid-stream kills. The explicit flag
                        # feeds the zero-truncation gate.
                        status = 599
                        truncated = True
                    break
            except aiohttp.ClientResponseError:
                raise              # raise_on_error path (status preserved)
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                if failovers < cfg.max_router_failovers \
                        and (toks or first is None) \
                        and self._rotate_url():
                    # Router replica died (or refused the connect):
                    # reconnect to the next replica. A stream that had
                    # token state re-enters via the cross-router resume
                    # headers so the peer splices the tail instead of
                    # restarting the answer (docs/ROUTER_SCALE.md).
                    failovers += 1
                    if toks:
                        headers["x-pstpu-resume-tokens"] = ",".join(
                            str(t) for t in toks
                        )
                        if seed is not None:
                            headers["x-pstpu-resume-seed"] = str(seed)
                    status = 599
                    continue
                if status == 200:
                    # The 200 stream had begun; the transport died before
                    # [DONE] — a truncation, same as the clean-EOF case.
                    truncated = True
                status = 599       # transport failure — always an error
                retry_after_hdr = None
                if cfg.raise_on_error:
                    raise
                break
        finish = time.monotonic()
        if 200 <= status < 300:
            self.messages.append({"role": "assistant", "content": answer})
        else:
            # Keep the conversation alternating for later rounds: a failed
            # round contributes no turns.
            self.messages.pop()
        self.records.append(RequestRecord(
            user=self.user_id, round=rnd, launch_time=launch,
            ttft=(first if first is not None else finish) - launch,
            finish_time=finish, prompt_tokens=prompt_tokens,
            generation_tokens=generation_tokens,
            status=status, sheds=sheds,
            retry_after=retry_after_hdr is not None,
            slo_class=cfg.slo_class,
            truncated=truncated,
            request_id=request_id,
            router_failovers=failovers,
        ))

    async def run(self, http: aiohttp.ClientSession, start_delay: float,
                  deadline: Optional[float] = None):
        if start_delay > 0:
            await asyncio.sleep(start_delay)
        for rnd in range(self.cfg.num_rounds):
            if deadline is not None and time.monotonic() >= deadline:
                break
            await self._one_round(http, rnd)


async def run_workload(cfg: WorkloadConfig) -> List[RequestRecord]:
    system_prompt = (
        "You are a helpful, knowledgeable assistant serving many users. "
        + synth_text(cfg.system_prompt_words)
    )
    sessions = [
        UserSession(cfg, u, system_prompt) for u in range(cfg.num_users)
    ]
    gap = (1.0 / cfg.qps) if cfg.qps else cfg.gap_between_users_s
    timeout = aiohttp.ClientTimeout(total=cfg.timeout_s)
    conn = aiohttp.TCPConnector(limit=0)
    deadline = (
        time.monotonic() + cfg.time_limit_s
        if cfg.time_limit_s is not None else None
    )
    async with aiohttp.ClientSession(timeout=timeout, connector=conn) as http:
        await asyncio.gather(*[
            s.run(http, u * gap, deadline) for u, s in enumerate(sessions)
        ])
    return [r for s in sessions for r in s.records]


def write_csv(records: List[RequestRecord], path: str) -> None:
    """Per-request CSV, column-compatible with the reference's plot.py
    (reads the 'ttft' column of {key}_output_{qps}.csv)."""
    import csv

    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["user", "round", "launch_time", "ttft", "finish_time",
                    "prompt_tokens", "generation_tokens", "generation_time"])
        for r in records:
            w.writerow([r.user, r.round, f"{r.launch_time:.6f}",
                        f"{r.ttft:.6f}", f"{r.finish_time:.6f}",
                        r.prompt_tokens, r.generation_tokens,
                        f"{r.generation_time:.6f}"])


def summarize(records: List[RequestRecord]) -> dict:
    """ProcessSummary-equivalent (reference multi-round-qa.py:435-512).

    Rate/latency metrics cover the OK records only; shed retries and
    terminal failures are accounted separately (``shed_total`` /
    ``errors_total`` — 503+Retry-After outcomes are shed, not errors)."""
    ok = [r for r in records if r.ok]
    shed_total = sum(r.sheds for r in records) + sum(
        1 for r in records if r.status == 503 and r.retry_after
    )
    errors_total = sum(
        1 for r in records
        if not r.ok and not (r.status == 503 and r.retry_after)
    )
    if not ok:
        return {"finished_requests": 0, "shed_total": shed_total,
                "errors_total": errors_total}
    start = min(r.launch_time for r in ok)
    end = max(r.finish_time for r in ok)
    total_time = max(end - start, 1e-9)
    ttfts = sorted(r.ttft for r in ok)
    gen_tokens = sum(r.generation_tokens for r in ok)
    return {
        "finished_requests": len(ok),
        "qps": len(ok) / total_time,
        "input_tokens_per_s": sum(r.prompt_tokens for r in ok) / total_time,
        "output_tokens_per_s": gen_tokens / total_time,
        "gen_speed_per_request": (
            sum(r.generation_tokens / r.generation_time for r in ok)
            / len(ok)
        ),
        "avg_ttft_s": sum(ttfts) / len(ttfts),
        "p50_ttft_s": ttfts[len(ttfts) // 2],
        "total_output_tokens": gen_tokens,
        "total_prompt_tokens": sum(r.prompt_tokens for r in ok),
        "elapsed_s": total_time,
        "shed_total": shed_total,
        "errors_total": errors_total,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base-url", default="http://localhost:8000")
    ap.add_argument("--model", default="llama-1b")
    ap.add_argument("--num-users", type=int, default=16)
    ap.add_argument("--num-rounds", type=int, default=4)
    ap.add_argument("--system-prompt-words", type=int, default=120)
    ap.add_argument("--question-words", type=int, default=12)
    ap.add_argument("--answer-tokens", type=int, default=64)
    ap.add_argument("--history-words", type=int, default=0,
                    help="per-user pre-seeded chat history (words of "
                         "alternating user/assistant turns) — the "
                         "reference's long-history sessions")
    ap.add_argument("--gap-between-users", type=float, default=0.0)
    ap.add_argument("--session-header", default="x-user-id")
    ap.add_argument("--api-key", default=None)
    ap.add_argument("--warmup-rounds", type=int, default=0,
                    help="Full extra passes run (and discarded) before the "
                         "timed workload, so device compile happens outside "
                         "the measurement")
    ap.add_argument("--qps", type=float, default=None,
                    help="session-launch rate (reference run.sh sweep "
                         "contract); overrides --gap-between-users")
    ap.add_argument("--time", type=float, default=None, dest="time_limit",
                    help="wall-clock bound: no new rounds start after this "
                         "many seconds (reference --time)")
    ap.add_argument("--output", default=None,
                    help="write a per-request CSV (plot.py-compatible "
                         "'ttft' column)")
    ap.add_argument("--sharegpt", default=None,
                    help="pre-processed ShareGPT json "
                         "(benchmarks/data_preprocessing.py output): "
                         "questions come from real conversations")
    args = ap.parse_args()
    sharegpt = None
    if args.sharegpt:
        with open(args.sharegpt) as f:
            sharegpt = json.load(f)
    cfg = WorkloadConfig(
        base_url=args.base_url, model=args.model, num_users=args.num_users,
        num_rounds=args.num_rounds,
        system_prompt_words=args.system_prompt_words,
        question_words=args.question_words, answer_tokens=args.answer_tokens,
        history_words=args.history_words,
        gap_between_users_s=args.gap_between_users,
        session_header=args.session_header, api_key=args.api_key,
        qps=args.qps, time_limit_s=args.time_limit, sharegpt=sharegpt,
    )
    if args.warmup_rounds > 0:
        warm_cfg = WorkloadConfig(**{**cfg.__dict__,
                                     "num_rounds": args.warmup_rounds,
                                     "tag": "warmup"})
        asyncio.run(run_workload(warm_cfg))
    records = asyncio.run(run_workload(cfg))
    if args.output:
        write_csv(records, args.output)
    print(json.dumps(summarize(records), indent=2))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
