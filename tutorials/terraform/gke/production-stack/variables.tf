# variables.tf
variable "setup_yaml" {
  description = "chart values for the stack (e.g. ../../assets/values-02-basic-config.yaml)"
  type        = string
  default     = "setup.yaml"
}

variable "prom_stack_yaml" {
  type    = string
  default = "kube-prom-stack.yaml"
}

variable "prom_adapter_yaml" {
  type    = string
  default = "prom-adapter.yaml"
}

variable "chart_path" {
  description = "local path to this repo's helm chart"
  type        = string
  default     = "../../../helm"
}
