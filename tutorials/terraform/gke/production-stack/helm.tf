# helm.tf — the TPU stack needs NO device-plugin release: GKE TPU node
# pools expose google.com/tpu natively (the reference installs the NVIDIA
# device plugin here). The chart is installed from the in-repo path.
resource "helm_release" "pstpu" {
  name  = "pstpu"
  chart = var.chart_path

  values = [
    file(var.setup_yaml)
  ]
}

resource "helm_release" "kube_prometheus_stack" {
  name             = "kube-prom-stack"
  repository       = "https://prometheus-community.github.io/helm-charts"
  chart            = "kube-prometheus-stack"
  namespace        = "monitoring"
  create_namespace = true
  wait             = true

  values = [
    file(var.prom_stack_yaml)
  ]
}

resource "helm_release" "prometheus_adapter" {
  name       = "prometheus-adapter"
  repository = "https://prometheus-community.github.io/helm-charts"
  chart      = "prometheus-adapter"
  namespace  = "monitoring"

  values = [
    file(var.prom_adapter_yaml)
  ]

  depends_on = [
    helm_release.kube_prometheus_stack
  ]
}
