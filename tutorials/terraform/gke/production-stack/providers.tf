# providers.tf — helm releases into the TPU cluster created by
# ../gke-infrastructure (run `gcloud container clusters get-credentials`
# first; the Makefile does).
provider "helm" {
  kubernetes {
    config_path = "~/.kube/config"
  }
}
