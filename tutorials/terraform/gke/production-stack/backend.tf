# backend.tf
terraform {
  required_providers {
    helm = {
      source = "hashicorp/helm"
    }
  }
}
