# services.tf
resource "google_project_service" "services" {
  for_each           = toset(var.gcp_services)
  disable_on_destroy = false
  project            = var.project
  service            = each.value
}

resource "time_sleep" "wait_60_seconds" {
  depends_on      = [google_project_service.services]
  create_duration = "60s"
}
