# backend.tf — local state by default; point at a GCS bucket for teams.
terraform {
  required_providers {
    google = {
      source = "hashicorp/google"
    }
    time = {
      source = "hashicorp/time"
    }
  }
}
