# providers.tf — GKE TPU cluster provisioning (TPU-native replacement for
# reference tutorials/terraform/gke/gke-infrastructure/providers.tf).
provider "google" {
  credentials = file(var.credentials_file)
  project     = var.project
  zone        = var.zone
}
