# node_pools.tf — a CPU pool for the router/controllers and a TPU v5e pool
# for the serving engines. TPU pools use placement_policy tpu_topology (the
# GKE TPU provisioning model) instead of the GPU path's guest_accelerator.
resource "google_container_node_pool" "cpu_pool" {
  name       = "${var.cluster_name}-cpu-pool"
  location   = var.zone
  cluster    = google_container_cluster.primary.name
  node_count = 1

  node_config {
    image_type   = "COS_CONTAINERD"
    machine_type = "e2-standard-8"
    disk_type    = "pd-balanced"
    disk_size_gb = 100

    metadata = {
      disable-legacy-endpoints = "true"
    }
    oauth_scopes = [
      "https://www.googleapis.com/auth/devstorage.read_only",
      "https://www.googleapis.com/auth/logging.write",
      "https://www.googleapis.com/auth/monitoring",
      "https://www.googleapis.com/auth/servicecontrol",
      "https://www.googleapis.com/auth/service.management.readonly",
      "https://www.googleapis.com/auth/trace.append",
    ]
    labels = {
      env = var.project
      app = "pstpu-router"
    }
  }
}

resource "google_container_node_pool" "tpu_pool" {
  name       = "${var.cluster_name}-tpu-pool"
  location   = var.zone
  cluster    = google_container_cluster.primary.name
  node_count = 2 # 2 x ct5lp-hightpu-4t = one v5e-8 (2x4) slice

  placement_policy {
    type         = "COMPACT"
    tpu_topology = var.tpu_topology
  }

  node_config {
    image_type   = "COS_CONTAINERD"
    machine_type = var.tpu_machine_type
    disk_type    = "pd-balanced"
    disk_size_gb = 100

    metadata = {
      disable-legacy-endpoints = "true"
    }
    oauth_scopes = [
      "https://www.googleapis.com/auth/devstorage.read_only",
      "https://www.googleapis.com/auth/logging.write",
      "https://www.googleapis.com/auth/monitoring",
      "https://www.googleapis.com/auth/servicecontrol",
      "https://www.googleapis.com/auth/service.management.readonly",
      "https://www.googleapis.com/auth/trace.append",
    ]
    labels = {
      env = var.project
      app = "pstpu-engine"
      "cloud.google.com/gke-tpu-accelerator" = "tpu-v5-lite-podslice"
      "cloud.google.com/gke-tpu-topology"    = var.tpu_topology
    }
    taint {
      key    = "google.com/tpu"
      value  = "present"
      effect = "NO_SCHEDULE"
    }
  }
}
