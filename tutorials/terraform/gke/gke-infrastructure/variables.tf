# variables.tf
variable "credentials_file" {
  description = "google credentials file"
  type        = string
  default     = "../credentials.json"
}

variable "project" {
  description = "GCP project id"
  type        = string
}

variable "cluster_name" {
  type    = string
  default = "production-stack"
}

variable "zone" {
  description = "zone with v5e capacity (see gcloud compute tpus locations)"
  type        = string
  default     = "us-central2-b"
}

# TPU node pools are keyed by machine type + topology, not guest
# accelerators (the GPU path's guest_accelerator block does not apply):
# ct5lp-hightpu-4t = v5e, 4 chips per VM; a 2x4 topology gives the v5e-8
# slice the BASELINE.md target configuration uses.
variable "tpu_machine_type" {
  type    = string
  default = "ct5lp-hightpu-4t"
}

variable "tpu_topology" {
  type    = string
  default = "2x4"
}

variable "gcp_services" {
  type = list(string)
  default = [
    "container.googleapis.com",
    "tpu.googleapis.com",
    "monitoring.googleapis.com",
    "logging.googleapis.com",
  ]
}
