# cluster.tf — mirrors the reference cluster config (release channel,
# managed prometheus, VPC-native) with TPU API enablement.
resource "google_container_cluster" "primary" {
  name     = var.cluster_name
  location = var.zone

  deletion_protection      = false
  remove_default_node_pool = true
  initial_node_count       = 1

  release_channel {
    channel = "REGULAR"
  }

  logging_config {
    enable_components = ["SYSTEM_COMPONENTS", "WORKLOADS"]
  }

  monitoring_config {
    enable_components = [
      "SYSTEM_COMPONENTS", "STORAGE", "POD", "DEPLOYMENT",
      "STATEFULSET", "DAEMONSET", "HPA", "CADVISOR", "KUBELET",
    ]
    managed_prometheus {
      enabled = true
    }
  }

  networking_mode = "VPC_NATIVE"
  network         = "default"
  subnetwork      = "default"
  ip_allocation_policy {}

  addons_config {
    horizontal_pod_autoscaling {
      disabled = false
    }
    http_load_balancing {
      disabled = false
    }
    gce_persistent_disk_csi_driver_config {
      enabled = true
    }
  }

  depends_on = [time_sleep.wait_60_seconds]
}
