# outputs.tf
output "cluster_name" {
  value = google_container_cluster.primary.name
}

output "tpu_pool" {
  value = google_container_node_pool.tpu_pool.name
}
