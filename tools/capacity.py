"""chips -> QPS capacity model from a measured multichip serving curve.

Input: a MULTICHIP_r*.json report recorded by ``bench.py --multichip-sweep``
(docs/PERF.md round 9) — REAL serving numbers from the stack harness
(router + engine subprocesses, zero-5xx enforced per point), never a dryrun
parity check. The model turns that curve into the numbers an operator (or
the Helm HPA stanzas) can actually provision against:

    QPS(chips) = per_chip_goodput x chips x scaling_efficiency(chips)
                 x slo_headroom / tokens_per_request

  * per_chip_goodput      — measured tok/s per chip at the 1-chip point;
  * scaling_efficiency    — measured tok/s-per-chip at n chips relative to
                            the 1-chip point (collectives + sharding
                            overhead make it <= 1);
  * slo_headroom          — fraction of raw throughput to provision at so
                            the PR-7 SLO attainment bars hold under the
                            arrival jitter a soak actually sees (the soak
                            recovery threshold, 0.9, is the default: a
                            fleet run at its exact roofline has zero slack
                            for a single fault);
  * tokens_per_request    — output tokens per finished request from the
                            same workload.

Beyond the largest measured mesh the fleet composes as DP replicas behind
the prefix-aware router (ROADMAP: router-level DP), so capacity scales
linearly in ENGINES of the best measured mesh shape — and the model emits
the concrete HPA targets for the exported autoscaling signals
(docs/SOAK.md): the per-engine ``pstpu:queue_depth`` average target
(Little's law: the concurrency one engine sustains at its SLO-headroom
QPS) and the router-level ``router_queue_depth`` sum at each fleet size.

With ``--router-report ROUTER_SWEEP_r*.json`` (bench.py --router-sweep,
docs/ROUTER_SCALE.md) the model additionally folds in the ROUTER tier:
the measured per-replica QPS ceiling becomes routers-per-QPS (how many
stateless router replicas a fleet of each size needs, at the same SLO
headroom) and the ``router_queue_depth`` HPA target for the router
Deployment's own autoscaler.

CLI:
    python -m tools.capacity MULTICHIP_r06.json [--target-qps N]
        [--slo-headroom 0.9] [--max-engines 8]
        [--router-report ROUTER_SWEEP_r04.json] [--json]
"""

import argparse
import json
import math
import sys
from typing import Dict, List, Optional


def _tokens_per_request(report: dict) -> float:
    """Mean output tokens per finished request across the sweep points."""
    toks = reqs = 0
    for run in report.get("runs", []):
        toks += run.get("total_output_tokens", 0)
        reqs += run.get("finished_requests", 0)
    if reqs:
        return toks / reqs
    # Fall back to the workload's nominal answer size.
    return float(report.get("workload", {}).get("max_tokens", 100))


def _avg_latency_s(report: dict) -> float:
    """Mean request latency over the sweep (Little's law on the measured
    closed loop: users concurrent sessions finishing at the measured QPS)."""
    users = report.get("workload", {}).get("users", 1)
    lats = [
        users / run["qps"]
        for run in report.get("runs", [])
        if run.get("qps")
    ]
    return sum(lats) / len(lats) if lats else 1.0


def capacity_model(
    report: dict,
    slo_headroom: float = 0.9,
    max_engines: int = 8,
) -> dict:
    """Pure function: multichip sweep report -> chips->QPS capacity table
    + HPA targets. See the module docstring for the math."""
    curve = report.get("curve") or []
    if not curve:
        raise ValueError("report carries no multichip curve")
    if not 0.0 < slo_headroom <= 1.0:
        raise ValueError(f"slo_headroom must be in (0, 1], got {slo_headroom}")
    base = curve[0]
    per_chip_goodput = base["tok_s"] / base["chips"]
    tokens_per_request = _tokens_per_request(report)
    avg_latency_s = _avg_latency_s(report)

    rows: List[Dict] = []
    # Measured mesh points: one engine, n chips.
    for pt in curve:
        qps_cap = (
            pt["tok_s"] * slo_headroom / tokens_per_request
        )
        rows.append({
            "chips": pt["chips"],
            "engines": 1,
            "chips_per_engine": pt["chips"],
            "tok_s": pt["tok_s"],
            "scaling_efficiency": pt.get(
                "scaling_efficiency",
                round((pt["tok_s"] / pt["chips"]) / per_chip_goodput, 4),
            ),
            "qps_capacity": round(qps_cap, 3),
            "measured": True,
        })
    # DP-replica extrapolation beyond the largest measured mesh: replicas
    # of the most tok/s-per-chip-efficient measured shape behind the
    # router. Linear in engines — each replica is an independent mesh; the
    # router's prefix-aware balancing is what makes the composition hold.
    best = max(curve, key=lambda p: p["tok_s"] / p["chips"])
    best_qps = best["tok_s"] * slo_headroom / tokens_per_request
    for engines in range(2, max(2, max_engines) + 1):
        rows.append({
            "chips": engines * best["chips"],
            "engines": engines,
            "chips_per_engine": best["chips"],
            "tok_s": round(engines * best["tok_s"], 2),
            "scaling_efficiency": best.get("scaling_efficiency", 1.0),
            "qps_capacity": round(engines * best_qps, 3),
            "measured": False,
        })
    rows.sort(key=lambda r: (r["chips"], r["engines"]))

    # HPA targets (docs/SOAK.md signals): the per-engine queue depth one
    # engine of the best shape sustains at its headroom QPS — requests in
    # flight = QPS x latency (Little) — and the router-level sum at each
    # fleet size. Floored at 1: a target of 0 would scale the fleet on
    # every single queued request.
    engine_queue_target = max(1, math.floor(best_qps * avg_latency_s))
    return {
        "model": report.get("model"),
        "backend": report.get("backend"),
        "slo_headroom": slo_headroom,
        "per_chip_goodput_tok_s": round(per_chip_goodput, 2),
        "tokens_per_request": round(tokens_per_request, 2),
        "avg_request_latency_s": round(avg_latency_s, 3),
        "best_mesh_chips": best["chips"],
        "table": rows,
        "hpa_targets": {
            # servingEngineSpec.autoscaling targetValue for the Pods
            # metric pstpu_queue_depth (helm/values-07-autoscaling).
            "pstpu_queue_depth_per_engine": engine_queue_target,
            # routerSpec.autoscaling Object metric router_queue_depth:
            # the fleet-wide backlog sum at which one MORE engine of the
            # best shape is warranted.
            "router_queue_depth_per_engine": engine_queue_target,
        },
    }


def router_tier_model(router_report: dict,
                      slo_headroom: float = 0.9) -> dict:
    """Pure function: router sweep report (bench.py --router-sweep) ->
    the router tier's per-replica QPS ceiling. Conservative: takes the
    WORST measured per-replica QPS across the sweep points (the marginal
    replica buys at least this much), then applies the same SLO headroom
    as the chip model."""
    curve = router_report.get("curve") or []
    per_replica = [
        p["qps"] / p["routers"] for p in curve
        if p.get("qps") and p.get("routers")
    ]
    if not per_replica:
        raise ValueError("router report carries no measured sweep curve")
    worst = min(per_replica)
    return {
        "measured_points": [
            {"routers": p.get("routers"), "qps": p.get("qps")}
            for p in curve
        ],
        "qps_per_router": round(worst, 3),
        "qps_ceiling_per_router": round(worst * slo_headroom, 3),
    }


def fold_router_tier(model: dict, router_report: dict) -> dict:
    """Fold a measured router-tier ceiling into a capacity model
    (docs/ROUTER_SCALE.md): every table row gains the stateless router
    replica count its QPS capacity needs, and the HPA targets gain the
    per-replica ``router_queue_depth`` bound the router Deployment's own
    autoscaler should hold (requests in flight per replica at its
    headroom QPS — Little's law, same as the engine target)."""
    tier = router_tier_model(router_report, model["slo_headroom"])
    ceiling = tier["qps_ceiling_per_router"] or 1.0
    for row in model["table"]:
        row["routers"] = max(1, math.ceil(row["qps_capacity"] / ceiling))
    model["router_tier"] = tier
    model["hpa_targets"]["router_queue_depth_per_router"] = max(
        1, math.floor(ceiling * model["avg_request_latency_s"])
    )
    return model


def engines_for_qps(model: dict, target_qps: float) -> dict:
    """Smallest fleet (engines of the best measured mesh shape) whose
    capacity covers ``target_qps``, with the HPA budget it implies."""
    per_engine = next(
        (r["qps_capacity"] for r in model["table"]
         if r["engines"] == 1 and r["chips"] == model["best_mesh_chips"]),
        None,
    )
    if not per_engine:
        raise ValueError("model has no per-engine capacity row")
    engines = max(1, math.ceil(target_qps / per_engine))
    out = {
        "target_qps": target_qps,
        "engines": engines,
        "chips": engines * model["best_mesh_chips"],
        "qps_capacity": round(engines * per_engine, 3),
        "router_queue_depth_scale_out_above": engines * model[
            "hpa_targets"
        ]["router_queue_depth_per_engine"],
    }
    tier = model.get("router_tier")
    if tier:
        out["routers"] = max(1, math.ceil(
            target_qps / (tier["qps_ceiling_per_router"] or 1.0)
        ))
    return out


def _render_table(model: dict) -> str:
    lines = [
        f"chips -> QPS capacity ({model['model']}, "
        f"headroom {model['slo_headroom']}, "
        f"{model['tokens_per_request']:.0f} tok/req, "
        f"per-chip goodput {model['per_chip_goodput_tok_s']} tok/s)",
        f"{'chips':>6} {'engines':>8} {'tok/s':>10} {'eff':>6} "
        f"{'QPS':>9}  source",
    ]
    with_routers = any("routers" in r for r in model["table"])
    if with_routers:
        lines[-1] += f" {'routers':>8}"
    for r in model["table"]:
        line = (
            f"{r['chips']:>6} {r['engines']:>8} {r['tok_s']:>10.1f} "
            f"{r['scaling_efficiency']:>6.2f} {r['qps_capacity']:>9.2f}  "
            f"{'measured' if r['measured'] else 'dp-extrapolated':<15}"
        )
        if with_routers:
            line += f" {r.get('routers', 1):>8}"
        lines.append(line)
    hpa = model["hpa_targets"]
    lines.append(
        f"HPA: pstpu_queue_depth per-engine target "
        f"{hpa['pstpu_queue_depth_per_engine']}; scale out when the "
        f"router_queue_depth sum exceeds "
        f"{hpa['router_queue_depth_per_engine']} x engines"
    )
    if "router_queue_depth_per_router" in hpa:
        tier = model["router_tier"]
        lines.append(
            f"Router tier: {tier['qps_ceiling_per_router']} QPS per "
            f"replica at headroom ({tier['qps_per_router']} measured); "
            f"scale the router Deployment when router_queue_depth per "
            f"replica exceeds {hpa['router_queue_depth_per_router']}"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="chips->QPS capacity model from a MULTICHIP_r*.json "
                    "serving scaling curve (bench.py --multichip-sweep)"
    )
    ap.add_argument("report", help="MULTICHIP_r*.json path")
    ap.add_argument("--slo-headroom", type=float, default=0.9,
                    help="fraction of raw throughput to provision at "
                         "(default 0.9 — the soak recovery attainment "
                         "threshold, docs/SOAK.md)")
    ap.add_argument("--max-engines", type=int, default=8,
                    help="DP-replica rows to extrapolate beyond the "
                         "largest measured mesh")
    ap.add_argument("--target-qps", type=float, default=None,
                    help="also print the smallest fleet covering this QPS")
    ap.add_argument("--router-report", default=None,
                    help="ROUTER_SWEEP_r*.json (bench.py --router-sweep): "
                         "fold the router tier's measured QPS ceiling in "
                         "— routers per fleet size + the per-replica "
                         "router_queue_depth HPA target "
                         "(docs/ROUTER_SCALE.md)")
    ap.add_argument("--json", action="store_true",
                    help="emit the model as JSON instead of the table")
    args = ap.parse_args(argv)

    with open(args.report) as f:
        report = json.load(f)
    model = capacity_model(
        report, slo_headroom=args.slo_headroom, max_engines=args.max_engines
    )
    if args.router_report:
        with open(args.router_report) as f:
            fold_router_tier(model, json.load(f))
    if args.target_qps is not None:
        model["provision"] = engines_for_qps(model, args.target_qps)
    if args.json:
        print(json.dumps(model, indent=1))
    else:
        print(_render_table(model))
        if "provision" in model:
            p = model["provision"]
            print(
                f"target {p['target_qps']} QPS -> {p['engines']} engines "
                f"({p['chips']} chips), capacity {p['qps_capacity']} QPS"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
