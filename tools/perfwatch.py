"""perfwatch: the performance-trajectory sentinel (docs/OBSERVABILITY.md).

The repo's perf history lives in checked-in round artifacts — BENCH_r01..r10,
BENCH_soak_r01..r04, MULTICHIP_r01..r06 — each with its own round-era schema.
perfwatch ingests EVERY artifact into one versioned trajectory document
(``PERF_TRAJECTORY.json``, schema ``pstpu-perf-trajectory-v1``), renders the
trend table inside docs/PERF.md's marker block (same freshness contract as
the gen_docs metrics tables), and gates fresh bench results against budgets
derived from comparable historical entries:

    python tools/perfwatch.py                      # rebuild trajectory + docs
    python tools/perfwatch.py --check-docs         # freshness gate (CI/PL004-style)
    python tools/perfwatch.py --ingest-line L.json --trajectory T.json
    python tools/perfwatch.py --check L.json --trajectory T.json [--tolerance 0.3]

``--check`` exits nonzero when the fresh bench JSON line regresses
output tok/s, p50 TTFT, kv_hit_rate, effective tokens/target-step, or the
zero-5xx bar past the budget derived from the best comparable entry (same
family + backend). With no comparable baseline it passes with a warning —
a new backend/workload cannot regress against nothing. The CI "Perf
sentinel" step ingests the honest smoke line into a scratch trajectory
first, so the gate is machine-speed independent: a doctored line must fail
against the very machine that produced it.

Loaders are structural (sniff the document shape, not the filename), so a
future round's artifact that keeps any known shape keeps ingesting; an
unrecognized shape becomes a zero-metric ``smoke`` entry rather than an
error — history is append-only and must never rot the sentinel.
"""

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

SCHEMA = "pstpu-perf-trajectory-v1"
TRAJECTORY_PATH = "PERF_TRAJECTORY.json"
DOCS_PATH = "docs/PERF.md"
#: Budget tolerance: fresh tok/s (and the other larger-is-better metrics)
#: may sit this far below the best comparable baseline before --check
#: fails; p50 TTFT may sit this far above. 0.3 keeps an honest re-run of
#: the same line green while a halved throughput (the CI doctored
#: self-test) is an unambiguous regression.
DEFAULT_TOLERANCE = 0.3

#: Metric keys a trajectory entry may carry. Larger-is-better unless noted.
METRIC_KEYS = (
    "output_tok_s",
    "p50_ttft_s",                        # smaller is better
    "kv_hit_rate",
    "hbm_bw_pct",
    "effective_tokens_per_target_step",
    "attainment",
    "errors_total",                      # must be 0
    "status_5xx",                        # must be 0
    "tok_per_s_per_chip",
    "scaling_efficiency",
)


def _num(v) -> Optional[float]:
    """Coerce to float, or None for anything non-numeric (schema drift in a
    historical artifact must degrade to a missing metric, not a crash)."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def _entry(source: str, family: str, variant: str, backend: str = "",
           metrics: Optional[Dict[str, Optional[float]]] = None,
           note: str = "") -> dict:
    clean = {k: _num(v) for k, v in (metrics or {}).items()
             if k in METRIC_KEYS and _num(v) is not None}
    e = {"source": source, "family": family, "variant": variant,
         "backend": backend or "", "metrics": clean}
    if note:
        e["note"] = note
    return e


def _from_bench_line(line: dict) -> Dict[str, Optional[float]]:
    """Metric dict from a bench.py one-line JSON record (any era: older
    lines simply lack the newer keys)."""
    out: Dict[str, Optional[float]] = {}
    if line.get("unit") == "tok/s":
        out["output_tok_s"] = line.get("value")
    for k in ("p50_ttft_s", "kv_hit_rate", "hbm_bw_pct",
              "effective_tokens_per_target_step", "errors_total",
              "tok_per_s_per_chip"):
        if k in line:
            out[k] = line.get(k)
    return out


# ------------------------------------------------------------------ loaders
def _load_wrapper(source, doc) -> List[dict]:
    """Round 1-6 shape: {n, cmd, rc, tail, parsed[, parsed_disagg]}."""
    out = []
    for key, variant in (("parsed", "stack"), ("parsed_disagg", "disagg")):
        line = doc.get(key)
        if isinstance(line, dict):
            out.append(_entry(source, "bench", variant,
                              line.get("backend", ""),
                              _from_bench_line(line)))
    if not out and doc.get("rc") is not None:
        out.append(_entry(source, "bench", "smoke",
                          metrics={"errors_total":
                                   0 if doc.get("rc") == 0 else 1},
                          note="wrapper with no parsed line"))
    return out


def _load_comparison(source, doc) -> List[dict]:
    """Round 7/8/9 shape: two named bench lines side by side."""
    out = []
    for variant in ("roundrobin", "prefix_aware", "spec_off", "spec_on",
                    "cold", "warm"):
        line = doc.get(variant)
        if isinstance(line, dict) and "value" in line:
            m = _from_bench_line(line)
            # Round 8 carries the effective-tokens factor at top level.
            eff = doc.get("effective_tokens_per_target_step")
            if isinstance(eff, dict) and _num(eff.get(variant)) is not None:
                m["effective_tokens_per_target_step"] = eff[variant]
            out.append(_entry(source, "bench", variant,
                              line.get("backend", ""), m))
    return out


def _load_spec_modes(source, doc) -> List[dict]:
    """Round 10 shape: modes{off,linear,tree,adaptive} x workloads."""
    out = []
    backend = doc.get("backend", "")
    eff = doc.get("effective_tokens_per_target_step", {})
    for mode, workloads in doc["modes"].items():
        if not isinstance(workloads, dict):
            continue
        for wl, stats in workloads.items():
            if not isinstance(stats, dict):
                continue
            m = {"output_tok_s": stats.get("output_tok_s"),
                 "effective_tokens_per_target_step":
                     stats.get("effective_tokens_per_target_step")}
            if m["effective_tokens_per_target_step"] is None and \
                    isinstance(eff.get(mode), dict):
                m["effective_tokens_per_target_step"] = eff[mode].get(wl)
            out.append(_entry(source, "bench", f"{mode}:{wl}", backend, m))
    return out


def _load_soak(source, doc) -> List[dict]:
    """pstpu-soak-v1: one entry per SLO class at the LAST ladder rung (peak
    sustained load), plus the run-wide zero-5xx bar."""
    out = []
    backend = doc.get("backend", "")
    ladder = doc.get("ladder") or []
    totals = doc.get("totals") or {}
    zero_5xx = doc.get("zero_5xx")
    if zero_5xx is None:
        zero_5xx = _num(totals.get("status_5xx")) == 0.0
    rung = ladder[-1] if ladder else {}
    for cls, stats in (rung.get("classes") or {}).items():
        if not isinstance(stats, dict):
            continue
        out.append(_entry(
            source, "soak", cls, backend,
            {"output_tok_s": stats.get("output_tok_s"),
             "p50_ttft_s": stats.get("p50_ttft_s"),
             "attainment": stats.get("attainment"),
             "status_5xx": stats.get("status_5xx"),
             "errors_total": stats.get("errors")},
        ))
    out.append(_entry(
        source, "soak", "totals", backend,
        {"errors_total": totals.get("errors"),
         "status_5xx": 0 if zero_5xx else
         (totals.get("status_5xx") if totals.get("status_5xx") is not None
          else 1)},
    ))
    return out


def _load_multichip_curve(source, doc) -> List[dict]:
    """MULTICHIP scaling-curve shape: one entry per chip-count point."""
    out = []
    backend = doc.get("backend", "")
    for point in doc.get("curve", []):
        if not isinstance(point, dict):
            continue
        out.append(_entry(
            source, "multichip", f"{point.get('chips', '?')}chip", backend,
            {"output_tok_s": point.get("tok_s"),
             "tok_per_s_per_chip": point.get("tok_per_s_per_chip"),
             "scaling_efficiency": point.get("scaling_efficiency"),
             "p50_ttft_s": point.get("p50_ttft_s"),
             "hbm_bw_pct": point.get("hbm_bw_pct"),
             "errors_total": point.get("errors_total")},
        ))
    return out


def _load_multichip_smoke(source, doc) -> List[dict]:
    """MULTICHIP r01-r05 shape: pass/fail smoke with no perf metrics."""
    ok = bool(doc.get("ok")) and not doc.get("skipped")
    return [_entry(
        source, "multichip", "smoke", "",
        {"errors_total": 0 if ok else 1},
        note=f"n_devices={doc.get('n_devices')} rc={doc.get('rc')}"
             f"{' skipped' if doc.get('skipped') else ''}",
    )]


def load_artifact(path: str) -> List[dict]:
    """Trajectory entries from one artifact, sniffed structurally."""
    source = os.path.basename(path)
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [_entry(source, "bench", "unreadable",
                       metrics={"errors_total": 1}, note=str(e))]
    if not isinstance(doc, dict):
        return [_entry(source, "bench", "smoke", note="non-object artifact")]
    if "parsed" in doc or ("rc" in doc and "cmd" in doc):
        return _load_wrapper(source, doc)
    if isinstance(doc.get("modes"), dict):
        return _load_spec_modes(source, doc)
    if doc.get("schema") == "pstpu-soak-v1" or "ladder" in doc:
        return _load_soak(source, doc)
    if isinstance(doc.get("curve"), list):
        return _load_multichip_curve(source, doc)
    if any(k in doc for k in ("roundrobin", "spec_off", "cold")):
        return _load_comparison(source, doc)
    if "n_devices" in doc:
        return _load_multichip_smoke(source, doc)
    if "value" in doc and "metric" in doc:
        # A bare bench one-line record checked in as-is.
        return [_entry(source, "bench", "stack", doc.get("backend", ""),
                       _from_bench_line(doc))]
    return [_entry(source, "bench", "smoke", note="unrecognized shape")]


def discover_artifacts(project_root: str) -> List[str]:
    pats = ("BENCH_r*.json", "BENCH_soak_r*.json", "MULTICHIP_r*.json")
    paths: List[str] = []
    for pat in pats:
        paths.extend(glob.glob(os.path.join(project_root, pat)))
    return sorted(paths)


def build_trajectory(project_root: str) -> dict:
    entries: List[dict] = []
    for path in discover_artifacts(project_root):
        entries.extend(load_artifact(path))
    return {"schema": SCHEMA, "entries": entries}


# --------------------------------------------------------------- validation
def validate_trajectory(doc) -> List[str]:
    """Hand-rolled schema gate (no jsonschema dependency): every problem as
    a human-readable string; [] means valid."""
    problems = []
    if not isinstance(doc, dict):
        return ["trajectory document is not an object"]
    if doc.get("schema") != SCHEMA:
        problems.append(f"schema is {doc.get('schema')!r}, want {SCHEMA!r}")
    entries = doc.get("entries")
    if not isinstance(entries, list):
        return problems + ["'entries' is not a list"]
    for i, e in enumerate(entries):
        where = f"entries[{i}]"
        if not isinstance(e, dict):
            problems.append(f"{where} is not an object")
            continue
        for key in ("source", "family", "variant", "backend"):
            if not isinstance(e.get(key), str):
                problems.append(f"{where}.{key} missing or not a string")
        if e.get("family") not in ("bench", "soak", "multichip"):
            problems.append(f"{where}.family {e.get('family')!r} unknown")
        metrics = e.get("metrics")
        if not isinstance(metrics, dict):
            problems.append(f"{where}.metrics missing or not an object")
            continue
        for k, v in metrics.items():
            if k not in METRIC_KEYS:
                problems.append(f"{where}.metrics has unknown key {k!r}")
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                problems.append(f"{where}.metrics[{k!r}] is not a number")
    return problems


# ---------------------------------------------------------------- the docs
def _fmt(v: Optional[float], digits: int = 2) -> str:
    if v is None:
        return "—"
    if float(v).is_integer() and abs(v) < 1e6:
        return str(int(v))
    return f"{v:.{digits}f}"


def render_trend_table(doc: dict) -> str:
    lines = [
        "| Source | Family | Variant | Backend | tok/s | p50 TTFT (s) "
        "| KV hit | Eff tok/step | Errors |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for e in doc["entries"]:
        m = e["metrics"]
        errors = m.get("errors_total")
        if errors is None:
            errors = m.get("status_5xx")
        lines.append(
            f"| {e['source']} | {e['family']} | {e['variant']} "
            f"| {e['backend'] or '—'} | {_fmt(m.get('output_tok_s'))} "
            f"| {_fmt(m.get('p50_ttft_s'), 4)} "
            f"| {_fmt(m.get('kv_hit_rate'), 3)} "
            f"| {_fmt(m.get('effective_tokens_per_target_step'), 4)} "
            f"| {_fmt(errors, 0)} |"
        )
    return "\n".join(lines)


def sync_docs(project_root: str, doc: dict, write: bool) -> List[str]:
    """Refresh (write=True) or report (write=False) the docs/PERF.md trend
    block; returns problem strings, [] when fresh. Reuses the gen_docs
    marker machinery so the freshness semantics match the metrics tables."""
    try:
        from tools.pstpu_lint.gen_docs import _update_block
    except ModuleNotFoundError:
        # Invoked as `python tools/perfwatch.py`: sys.path[0] is tools/,
        # not the repo root the package imports resolve from.
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from tools.pstpu_lint.gen_docs import _update_block

    path = os.path.join(project_root, DOCS_PATH)
    if not os.path.exists(path):
        return [f"{DOCS_PATH}: missing"]
    with open(path, encoding="utf-8") as f:
        text = f.read()
    updated = _update_block(text, "perf-trajectory", "trend",
                            render_trend_table(doc))
    if updated is None:
        return [f"{DOCS_PATH}: missing the "
                f"<!-- pstpu-perf-trajectory:BEGIN trend --> marker block"]
    if updated != text:
        if write:
            with open(path, "w", encoding="utf-8") as f:
                f.write(updated)
        else:
            return [f"{DOCS_PATH}: trend table out of date; run "
                    f"python tools/perfwatch.py"]
    return []


# --------------------------------------------------------------- the gate
def _comparable(entries: List[dict], fresh_backend: str) -> List[dict]:
    return [e for e in entries
            if e.get("family") == "bench"
            and (e.get("backend") or "") == (fresh_backend or "")
            and e.get("metrics")]


def check_line(doc: dict, line: dict,
               tolerance: float = DEFAULT_TOLERANCE) -> List[str]:
    """Budget gate: regression strings for a fresh bench line against the
    best comparable trajectory entries; [] means within budget."""
    problems = []
    fresh = _from_bench_line(line)
    errors = fresh.get("errors_total")
    if errors is not None and errors > 0:
        problems.append(f"zero-5xx bar: fresh line has "
                        f"errors_total={int(errors)} (must be 0)")
    pool = _comparable(doc.get("entries", []), line.get("backend", ""))
    if not pool:
        print(f"perfwatch: no comparable baseline (family=bench, "
              f"backend={line.get('backend', '')!r}) — passing with a "
              f"warning", file=sys.stderr)
        return problems

    def best(key, better=max):
        vals = [e["metrics"][key] for e in pool if key in e["metrics"]]
        return better(vals) if vals else None

    # Larger-is-better floors.
    for key, label in (("output_tok_s", "tok/s"),
                       ("kv_hit_rate", "kv_hit_rate"),
                       ("effective_tokens_per_target_step",
                        "effective tokens/target-step")):
        base = best(key)
        got = fresh.get(key)
        if base is None or base <= 0 or got is None:
            continue
        floor = base * (1.0 - tolerance)
        if got < floor:
            problems.append(
                f"{label} regression: {got:.4g} < budget {floor:.4g} "
                f"(best comparable {base:.4g}, tolerance {tolerance:.0%})"
            )
    # Smaller-is-better ceiling.
    base = best("p50_ttft_s", better=min)
    got = fresh.get("p50_ttft_s")
    if base is not None and base > 0 and got is not None:
        ceiling = base * (1.0 + tolerance)
        if got > ceiling:
            problems.append(
                f"p50 TTFT regression: {got:.4g}s > budget {ceiling:.4g}s "
                f"(best comparable {base:.4g}s, tolerance {tolerance:.0%})"
            )
    return problems


def ingest_line(doc: dict, line: dict, source: str = "fresh") -> dict:
    doc.setdefault("entries", []).append(_entry(
        source, "bench", "stack", line.get("backend", ""),
        _from_bench_line(line),
    ))
    return doc


# --------------------------------------------------------------------- CLI
def _load_json(path: str):
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _load_trajectory(path: str) -> dict:
    doc = _load_json(path)
    problems = validate_trajectory(doc)
    if problems:
        for p in problems:
            print(f"perfwatch: {path}: {p}", file=sys.stderr)
        raise SystemExit(2)
    return doc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python tools/perfwatch.py",
        description="Perf-trajectory sentinel: ingest round artifacts, "
                    "render the docs trend table, gate fresh bench lines.",
    )
    p.add_argument("--project-root", default=".")
    p.add_argument("--trajectory", default=None,
                   help=f"trajectory file (default: {TRAJECTORY_PATH} "
                        f"under --project-root)")
    p.add_argument("--check-docs", action="store_true",
                   help="verify PERF_TRAJECTORY.json and the docs/PERF.md "
                        "trend table are up to date (exit 1 when stale)")
    p.add_argument("--ingest-line", metavar="LINE_JSON",
                   help="append a bench one-line JSON record to the "
                        "trajectory file")
    p.add_argument("--check", metavar="LINE_JSON",
                   help="gate a bench one-line JSON record against the "
                        "trajectory budgets (exit 1 on regression)")
    p.add_argument("--source", default="fresh",
                   help="source label recorded by --ingest-line")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="budget tolerance fraction (default %(default)s)")
    args = p.parse_args(argv)
    root = os.path.abspath(args.project_root)
    traj_path = args.trajectory or os.path.join(root, TRAJECTORY_PATH)

    if args.check:
        doc = _load_trajectory(traj_path)
        problems = check_line(doc, _load_json(args.check), args.tolerance)
        for prob in problems:
            print(f"perfwatch: REGRESSION: {prob}", file=sys.stderr)
        if not problems:
            print("perfwatch: within budget")
        return 1 if problems else 0

    if args.ingest_line:
        doc = (_load_trajectory(traj_path) if os.path.exists(traj_path)
               else {"schema": SCHEMA, "entries": []})
        ingest_line(doc, _load_json(args.ingest_line), args.source)
        problems = validate_trajectory(doc)
        if problems:
            for prob in problems:
                print(f"perfwatch: {prob}", file=sys.stderr)
            return 2
        with open(traj_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"perfwatch: ingested {args.ingest_line} into {traj_path}")
        return 0

    built = build_trajectory(root)
    problems = validate_trajectory(built)
    if problems:
        for prob in problems:
            print(f"perfwatch: built trajectory invalid: {prob}",
                  file=sys.stderr)
        return 2

    if args.check_docs:
        stale = []
        if not os.path.exists(traj_path):
            stale.append(f"{traj_path}: missing; run python "
                         f"tools/perfwatch.py")
        else:
            current = _load_json(traj_path)
            if current != built:
                stale.append(f"{traj_path}: out of date with the checked-in "
                             f"artifacts; run python tools/perfwatch.py")
            stale.extend(validate_trajectory(current))
        stale.extend(sync_docs(root, built, write=False))
        for prob in stale:
            print(f"perfwatch: {prob}", file=sys.stderr)
        return 1 if stale else 0

    with open(traj_path, "w", encoding="utf-8") as f:
        json.dump(built, f, indent=1)
        f.write("\n")
    print(f"perfwatch: wrote {traj_path} "
          f"({len(built['entries'])} entries from "
          f"{len(discover_artifacts(root))} artifacts)")
    for prob in sync_docs(root, built, write=True):
        print(f"perfwatch: {prob}", file=sys.stderr)
        return 2
    print(f"perfwatch: refreshed {DOCS_PATH} trend table")
    return 0


if __name__ == "__main__":
    sys.exit(main())
