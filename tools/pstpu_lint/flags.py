"""Static argparse flag extraction, shared by PL006 and the docs generator.

Walks ``add_argument`` calls with a constant ``--flag`` first argument and
records the option string, dest, rendered default, and help text. Defaults
that are not literals (e.g. ``os.environ.get(...)``) render as ``env``.

Also scans the helm chart (stdlib-only, regex over the template text) for
``tpuConfig.*``/``routerSpec.*`` value references and the ``--flag`` each
one renders next to, plus the key sets declared in ``values.yaml`` and
``values.schema.json`` — the inputs of PL006's helm-drift leg.
"""

import ast
import json
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set


@dataclass
class Flag:
    option: str          # "--retry-max-attempts"
    dest: str            # "retry_max_attempts"
    default: str         # rendered default for docs tables
    help: str
    line: int


def _const(node: ast.AST):
    if isinstance(node, ast.Constant):
        return node.value
    return None


def _render_default(node: Optional[ast.AST], action: Optional[str]) -> str:
    if action in ("store_true",):
        return "off"
    if node is None:
        return ""
    if isinstance(node, ast.Constant):
        if node.value is None:
            return "unset"
        return str(node.value)
    if isinstance(node, (ast.List, ast.Tuple)):
        vals = [_const(e) for e in node.elts]
        if all(v is not None for v in vals):
            return str(list(vals))
        return "computed"
    return "env" if "environ" in ast.dump(node) else "computed"


def scan_flags(source: str) -> List[Flag]:
    flags: List[Flag] = []
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args):
            continue
        option = _const(node.args[0])
        if not isinstance(option, str) or not option.startswith("-"):
            continue
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        action = _const(kw.get("action"))
        dest = _const(kw.get("dest")) or option.lstrip("-").replace("-", "_")
        help_text = _const(kw.get("help")) or ""
        choices = kw.get("choices")
        if choices is not None:
            rendered = None
            if isinstance(choices, (ast.List, ast.Tuple)):
                vals = [_const(e) for e in choices.elts]
                if all(v is not None for v in vals):
                    rendered = ", ".join(str(v) for v in vals)
            if rendered:
                # ", "-joined, not "|": these strings land in markdown
                # table cells where a raw pipe splits the row.
                help_text = (f"{help_text} " if help_text else "") \
                    + f"(choices: {rendered})"
        flags.append(Flag(
            option=option, dest=dest,
            default=_render_default(kw.get("default"), action),
            help=" ".join(help_text.split()), line=node.lineno,
        ))
    return flags


# ------------------------------------------------------------- helm chart
@dataclass
class HelmWiring:
    """One ``tpuConfig.X``/``routerSpec.Y`` value reference in a template,
    with the ``--flag`` it renders next to (None = non-flag use: image
    fields, labels, nodeSelector, probes...)."""

    section: str          # "tpuConfig" | "routerSpec"
    key: str              # "tensorParallelSize"
    flag: Optional[str]   # "--tensor-parallel-size" or None
    line: int

    @property
    def dotted(self) -> str:
        return f"{self.section}.{self.key}"


_HELM_KEY_RE = re.compile(r"\b(tpuConfig|routerSpec)\.(\w+)")
_HELM_FLAG_RE = re.compile(r'"(--[a-z][a-z0-9-]*)"')


def scan_helm_wirings(template_source: str) -> List[HelmWiring]:
    """Pair every tpuConfig./routerSpec. reference with the CLI flag
    rendered within two lines of it (helm args lists put the flag literal
    on the line above its value; ``if not X`` negations put it below)."""
    lines = template_source.splitlines()
    out: List[HelmWiring] = []
    for i, line in enumerate(lines):
        for m in _HELM_KEY_RE.finditer(line):
            flag = None
            for dj in (0, -1, 1, -2, 2):   # nearest line first
                j = i + dj
                if 0 <= j < len(lines):
                    fm = _HELM_FLAG_RE.search(lines[j])
                    if fm:
                        flag = fm.group(1)
                        break
            out.append(HelmWiring(m.group(1), m.group(2), flag, i + 1))
    return out


def scan_helm_schema_keys(schema_source: str) -> Dict[str, Set[str]]:
    """{'tpuConfig': {...}, 'routerSpec': {...}} property-name sets from
    values.schema.json."""
    schema = json.loads(schema_source)
    out: Dict[str, Set[str]] = {"tpuConfig": set(), "routerSpec": set()}
    try:
        tpu = (schema["properties"]["servingEngineSpec"]["properties"]
               ["modelSpec"]["items"]["properties"]["tpuConfig"]
               ["properties"])
        out["tpuConfig"] = set(tpu)
    except KeyError:
        pass
    try:
        out["routerSpec"] = set(
            schema["properties"]["routerSpec"]["properties"])
    except KeyError:
        pass
    return out


def scan_helm_values_keys(values_source: str) -> Dict[str, Set[str]]:
    """Top-level key names under the ``routerSpec:`` mapping in
    values.yaml (two-space indent; comments skipped). tpuConfig carries no
    defaults in values.yaml (modelSpec is an empty list), so only
    routerSpec is scanned."""
    out: Dict[str, Set[str]] = {"routerSpec": set()}
    in_section = False
    for line in values_source.splitlines():
        if re.match(r"^routerSpec:\s*$", line):
            in_section = True
            continue
        if in_section:
            if line.strip() and not line.startswith(" ") \
                    and not line.lstrip().startswith("#"):
                break   # next top-level key ends the section
            m = re.match(r"^  (\w+):", line)
            if m:
                out["routerSpec"].add(m.group(1))
    return out
