"""Static argparse flag extraction, shared by PL006 and the docs generator.

Walks ``add_argument`` calls with a constant ``--flag`` first argument and
records the option string, dest, rendered default, and help text. Defaults
that are not literals (e.g. ``os.environ.get(...)``) render as ``env``.
"""

import ast
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class Flag:
    option: str          # "--retry-max-attempts"
    dest: str            # "retry_max_attempts"
    default: str         # rendered default for docs tables
    help: str
    line: int


def _const(node: ast.AST):
    if isinstance(node, ast.Constant):
        return node.value
    return None


def _render_default(node: Optional[ast.AST], action: Optional[str]) -> str:
    if action in ("store_true",):
        return "off"
    if node is None:
        return ""
    if isinstance(node, ast.Constant):
        if node.value is None:
            return "unset"
        return str(node.value)
    if isinstance(node, (ast.List, ast.Tuple)):
        vals = [_const(e) for e in node.elts]
        if all(v is not None for v in vals):
            return str(list(vals))
        return "computed"
    return "env" if "environ" in ast.dump(node) else "computed"


def scan_flags(source: str) -> List[Flag]:
    flags: List[Flag] = []
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args):
            continue
        option = _const(node.args[0])
        if not isinstance(option, str) or not option.startswith("-"):
            continue
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        action = _const(kw.get("action"))
        dest = _const(kw.get("dest")) or option.lstrip("-").replace("-", "_")
        help_text = _const(kw.get("help")) or ""
        choices = kw.get("choices")
        if choices is not None:
            rendered = None
            if isinstance(choices, (ast.List, ast.Tuple)):
                vals = [_const(e) for e in choices.elts]
                if all(v is not None for v in vals):
                    rendered = ", ".join(str(v) for v in vals)
            if rendered:
                # ", "-joined, not "|": these strings land in markdown
                # table cells where a raw pipe splits the row.
                help_text = (f"{help_text} " if help_text else "") \
                    + f"(choices: {rendered})"
        flags.append(Flag(
            option=option, dest=dest,
            default=_render_default(kw.get("default"), action),
            help=" ".join(help_text.split()), line=node.lineno,
        ))
    return flags
