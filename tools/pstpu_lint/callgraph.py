"""Module-local call graph: which function bodies run on the event loop.

PL001 must flag ``time.sleep`` in a helper a request handler calls, but NOT
in a thread worker (the stats scraper loop, the K8s watch loop) or in a
callable handed to ``run_in_executor``/``threading.Thread`` — those run off
the loop by construction. The distinction is call-graph *context*, not
file-level waivers:

  * seeds: every ``async def`` body;
  * edges: plain same-module calls — bare names resolved against enclosing
    function scopes then module level, ``self.method()`` resolved against
    the enclosing class;
  * non-edges: passing a function as a value (``Thread(target=f)``,
    ``loop.run_in_executor(None, f)``, ``task.add_done_callback(f)``) is a
    reference, not a call, so thread/executor targets are never pulled into
    the async context unless something async also calls them directly.

Cross-module calls are not resolved (documented limitation — the suite is
per-module by design; the repo's blocking helpers and their async callers
live in the same module).
"""

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class FunctionInfo:
    qualname: str
    node: ast.AST                      # FunctionDef | AsyncFunctionDef
    is_async: bool
    enclosing_class: Optional[str]     # qualname of the owning class
    parent_function: Optional[str]     # qualname of the enclosing function
    calls: List[Tuple[str, int]] = field(default_factory=list)
    # (callee qualname, call line) — resolved, module-local


def _own_statements(node: ast.AST):
    """Walk a function body WITHOUT descending into nested function/class
    definitions (their bodies are separate call-graph nodes)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


class _Collector(ast.NodeVisitor):
    def __init__(self):
        self.functions: Dict[str, FunctionInfo] = {}
        self._scope: List[Tuple[str, str]] = []   # (kind, name) kind∈{c,f}

    def _qual(self, name: str) -> str:
        return ".".join([n for _, n in self._scope] + [name])

    def _visit_func(self, node, is_async: bool):
        qual = self._qual(node.name)
        encl_class = None
        parent_fn = None
        # Innermost enclosing class (``self`` in a closure still refers to
        # that class's instance) ...
        for i in range(len(self._scope) - 1, -1, -1):
            if self._scope[i][0] == "c":
                encl_class = ".".join(n for _, n in self._scope[:i + 1])
                break
        # ... and innermost enclosing function, but not across a class
        # boundary (a method is not "nested in" the function defining its
        # class for name-resolution purposes).
        for i in range(len(self._scope) - 1, -1, -1):
            if self._scope[i][0] == "f":
                parent_fn = ".".join(n for _, n in self._scope[:i + 1])
                break
            if self._scope[i][0] == "c":
                break
        self.functions[qual] = FunctionInfo(
            qualname=qual, node=node, is_async=is_async,
            enclosing_class=encl_class, parent_function=parent_fn,
        )
        self._scope.append(("f", node.name))
        self.generic_visit(node)
        self._scope.pop()

    def visit_FunctionDef(self, node):
        self._visit_func(node, is_async=False)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node, is_async=True)

    def visit_ClassDef(self, node):
        self._scope.append(("c", node.name))
        self.generic_visit(node)
        self._scope.pop()


class CallGraph:
    def __init__(self, tree: ast.AST):
        collector = _Collector()
        collector.visit(tree)
        self.functions = collector.functions
        self._resolve_calls()

    # ------------------------------------------------------------ resolution
    def _resolve_name(self, caller: FunctionInfo, name: str) -> Optional[str]:
        """A bare-name call: nested defs of enclosing functions first
        (innermost out), then module level."""
        fn: Optional[FunctionInfo] = caller
        while fn is not None:
            nested = f"{fn.qualname}.{name}"
            if nested in self.functions:
                return nested
            fn = self.functions.get(fn.parent_function) \
                if fn.parent_function else None
        return name if name in self.functions else None

    def _resolve_self_method(self, caller: FunctionInfo,
                             method: str) -> Optional[str]:
        if caller.enclosing_class is None:
            return None
        qual = f"{caller.enclosing_class}.{method}"
        return qual if qual in self.functions else None

    def _resolve_calls(self) -> None:
        for info in self.functions.values():
            for node in _own_statements(info.node):
                if not isinstance(node, ast.Call):
                    continue
                target = None
                if isinstance(node.func, ast.Name):
                    target = self._resolve_name(info, node.func.id)
                elif (isinstance(node.func, ast.Attribute)
                      and isinstance(node.func.value, ast.Name)
                      and node.func.value.id in ("self", "cls")):
                    target = self._resolve_self_method(info, node.func.attr)
                if target is not None:
                    info.calls.append((target, node.lineno))

    # ------------------------------------------------------------- traversal
    def async_context(self) -> Dict[str, List[str]]:
        """qualname -> chain of callers from an async seed (the seed itself
        maps to a one-element chain). Sync functions only reachable as
        thread/executor targets never appear here."""
        chains: Dict[str, List[str]] = {}
        frontier = []
        for qual, info in self.functions.items():
            if info.is_async:
                chains[qual] = [qual]
                frontier.append(qual)
        while frontier:
            qual = frontier.pop()
            for callee, _line in self.functions[qual].calls:
                if callee in chains:
                    continue
                chains[callee] = chains[qual] + [callee]
                frontier.append(callee)
        return chains
