"""pstpu-lint: in-repo static analysis for the serving stack.

An AST-based rule suite (stdlib ``ast``/``tokenize`` only, no runtime deps)
tuned to this codebase's real failure modes: a thin asyncio router fronting
many engines lives or dies on "never block the event loop, never leak a
task, never let a metric silently drift". Each rule has a stable code; see
docs/LINTING.md for the catalogue with before/after examples.

  PL001  blocked-event-loop       sync I/O reachable inside async defs
  PL002  fire-and-forget-task     dropped asyncio.create_task handles
  PL003  swallowed-exception      silent catch-alls in the data plane
  PL004  metrics-drift            renderer/registry/docs series consistency
  PL005  await-under-lock         await while holding a threading lock
  PL006  config-flag-drift        argparse flags unreferenced/undocumented
  PL000  waiver-hygiene           reason-less or stale lint waivers

Findings are suppressed per line with a linted waiver comment::

    time.sleep(0.1)  # pstpu-lint: allow[PL001] reason=startup-only probe

Usage: ``python -m tools.pstpu_lint [paths] [--format text|github]``.
"""

from tools.pstpu_lint.core import Finding, main, run_lint  # noqa: F401

__all__ = ["Finding", "main", "run_lint"]
