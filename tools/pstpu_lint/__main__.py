"""CLI entry point: ``python -m tools.pstpu_lint [paths]``."""

import sys

from tools.pstpu_lint.core import main

if __name__ == "__main__":
    sys.exit(main())
