"""Module-local model of JAX dispatch surfaces, shared by PL007/PL008.

Two things the donation and trace-safety rules both need:

  * which bindings in a module hold a jitted callable, and with what
    ``donate_argnums`` / ``static_argnames`` (the *dispatch signature*) —
    from direct assignments (``self._decode = jax.jit(self._decode_impl,
    donate_argnums=...)``), decorated defs (``@jax.jit`` /
    ``@partial(jax.jit, ...)``), and one level of factory indirection
    (``self._reset = self._make_reset()`` where ``_make_reset`` returns a
    ``jax.jit(...)``);
  * which function bodies are *traced* — the callables handed to
    ``jax.jit``/``pjit``/``jax.lax.scan``/``while_loop``/``cond``/
    ``fori_loop``/``shard_map``/``vmap``, plus everything they call per the
    module-local call graph (tools/pstpu_lint/callgraph.py).

Resolution is module-local by design, matching the rest of the suite: the
repo's dispatch wrappers and their call sites live in the same module
(engine/runner.py), and cross-module jit handoff would be a smell the
human reviewer should see anyway.
"""

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tools.pstpu_lint.callgraph import CallGraph

# Transform entry points that take a callable first argument and trace it.
_TRACERS = {
    "jit", "pjit", "vmap", "grad", "value_and_grad", "checkpoint", "remat",
}
_LAX_TRACERS = {"scan", "while_loop", "cond", "fori_loop", "map",
                "associated_scan", "associative_scan", "switch"}
_SHARD_TRACERS = {"shard_map"}


@dataclass
class JitBinding:
    """One binding that holds a jitted callable."""

    key: str                     # "self._decode" or a bare name
    impl_qual: Optional[str]     # module-local qualname of the traced fn
    donate: Tuple[int, ...]      # donate_argnums (positional, call-site)
    static_names: Tuple[str, ...]  # static_argnames
    line: int


@dataclass
class JaxModel:
    graph: CallGraph
    bindings: Dict[str, JitBinding] = field(default_factory=dict)
    # qualnames of function bodies that are traced entry points, with the
    # static-argname set that applies to their parameters ("" entries for
    # scan/cond/shard_map bodies, where every parameter is tracer-typed).
    seeds: Dict[str, Tuple[str, ...]] = field(default_factory=dict)

    def traced_context(self) -> Dict[str, List[str]]:
        """qualname -> caller chain from a traced seed (the seed maps to a
        one-element chain), via plain module-local calls."""
        chains: Dict[str, List[str]] = {}
        frontier = []
        for qual in self.seeds:
            chains[qual] = [qual]
            frontier.append(qual)
        while frontier:
            qual = frontier.pop()
            info = self.graph.functions.get(qual)
            if info is None:
                continue
            for callee, _line in info.calls:
                if callee in chains:
                    continue
                chains[callee] = chains[qual] + [callee]
                frontier.append(callee)
        return chains


def _const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _const_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _callable_name(fn: ast.AST) -> str:
    """'jit' for jax.jit / pjit / bare jit, 'scan' for jax.lax.scan, ..."""
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _is_tracer_call(node: ast.Call) -> str:
    """'' unless this call traces its first argument; else the kind
    ('jit' | 'lax' | 'shard')."""
    name = _callable_name(node.func)
    if name in _TRACERS:
        return "jit"
    if name in _SHARD_TRACERS:
        return "shard"
    if name in _LAX_TRACERS:
        # Guard against domain methods named scan/map: require a
        # jax/lax-ish receiver (jax.lax.scan, lax.scan) or bare name.
        fn = node.func
        if isinstance(fn, ast.Attribute):
            root = fn.value
            rootname = (
                root.attr if isinstance(root, ast.Attribute)
                else root.id if isinstance(root, ast.Name) else ""
            )
            if rootname not in ("lax", "jax"):
                return ""
        return "lax"
    return ""


def _jit_signature(node: ast.Call):
    """(donate_nums, donate_names, static_names) from a jit/pjit call's
    keywords. ``donate_argnames`` entries are resolved to positions later,
    against the traced function's parameter list."""
    donate: Tuple[int, ...] = ()
    donate_names: Tuple[str, ...] = ()
    static: Tuple[str, ...] = ()
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            donate = _const_int_tuple(kw.value) or ()
        elif kw.arg == "donate_argnames":
            donate_names = _const_str_tuple(kw.value) or ()
        elif kw.arg in ("static_argnames",):
            static = _const_str_tuple(kw.value) or ()
    return donate, donate_names, static


def _donate_positions(graph: CallGraph, impl_qual: Optional[str],
                      nums: Tuple[int, ...],
                      names: Tuple[str, ...]) -> Tuple[int, ...]:
    """Positional donate set: explicit argnums plus argnames resolved
    against the traced function's parameters (self/cls excluded, matching
    the call-site positional layout of a bound-method jit)."""
    out = list(nums)
    if names and impl_qual is not None:
        info = graph.functions.get(impl_qual)
        if info is not None:
            args = info.node.args
            params = [a.arg for a in args.posonlyargs + args.args
                      if a.arg not in ("self", "cls")]
            for name in names:
                if name in params:
                    out.append(params.index(name))
    return tuple(sorted(set(out)))


def _binding_key(target: ast.AST) -> Optional[str]:
    if isinstance(target, ast.Name):
        return target.id
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id in ("self", "cls")):
        return f"self.{target.attr}"
    return None


def _resolve_callable(graph: CallGraph, owner_qual: str,
                      node: ast.AST) -> Optional[str]:
    """Module-local qualname of a callable expression (Name or
    self.method), resolved from the function whose body contains it."""
    info = graph.functions.get(owner_qual)
    if info is None:
        return None
    if isinstance(node, ast.Name):
        return graph._resolve_name(info, node.id)
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")):
        return graph._resolve_self_method(info, node.attr)
    return None


def build(tree: ast.AST) -> JaxModel:
    graph = CallGraph(tree)
    model = JaxModel(graph=graph)

    # Map every statement to the function whose body owns it, so tracer
    # calls found anywhere resolve names from the right scope.
    owner_of: Dict[int, str] = {}
    for qual, info in graph.functions.items():
        from tools.pstpu_lint.callgraph import _own_statements

        for node in _own_statements(info.node):
            owner_of[id(node)] = qual

    for node in ast.walk(tree):
        # ---- decorated defs: @jax.jit / @partial(jax.jit, ...) ----------
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                donate: Tuple[int, ...] = ()
                dnames: Tuple[str, ...] = ()
                static: Tuple[str, ...] = ()
                is_jit = False
                if _callable_name(deco) in _TRACERS:
                    is_jit = True
                elif isinstance(deco, ast.Call):
                    if _callable_name(deco.func) in _TRACERS:
                        is_jit = True
                        donate, dnames, static = _jit_signature(deco)
                    elif (_callable_name(deco.func) == "partial"
                          and deco.args
                          and _callable_name(deco.args[0]) in _TRACERS):
                        is_jit = True
                        donate, dnames, static = _jit_signature(deco)
                if is_jit:
                    qual = _qual_of_def(graph, node)
                    if qual is not None:
                        model.seeds.setdefault(qual, static)
                        model.bindings[node.name] = JitBinding(
                            node.name, qual,
                            _donate_positions(graph, qual, donate, dnames),
                            static, node.lineno)
            continue

        if not isinstance(node, ast.Call):
            continue
        kind = _is_tracer_call(node)
        if not kind or not node.args:
            continue
        owner = owner_of.get(id(node))
        # Tracer calls nested in expressions still need an owner; walk up
        # is not available, so fall back to scanning all functions whose
        # span contains the call line (rare path; assignments cover most).
        if owner is None:
            owner = _owner_by_span(graph, node.lineno)
        target_fn = _resolve_callable(graph, owner, node.args[0]) \
            if owner else None
        if target_fn is None and isinstance(node.args[0], ast.Name):
            target_fn = node.args[0].id \
                if node.args[0].id in graph.functions else None
        if kind == "jit":
            _donate, _dnames, static = _jit_signature(node)
            if target_fn is not None:
                model.seeds.setdefault(target_fn, static)
        elif target_fn is not None:
            # Every parameter of a scan/cond/shard_map body is traced.
            model.seeds.setdefault(target_fn, ())

    # ---- bindings from assignments ------------------------------------
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        key = _binding_key(node.targets[0])
        if key is None or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        if _is_tracer_call(call) == "jit" and call.args:
            donate, dnames, static = _jit_signature(call)
            owner = owner_of.get(id(node)) or _owner_by_span(
                graph, node.lineno)
            impl = _resolve_callable(graph, owner, call.args[0]) \
                if owner else None
            model.bindings[key] = JitBinding(
                key, impl,
                _donate_positions(graph, impl, donate, dnames),
                static, node.lineno)
            continue
        # One level of factory indirection: self._x = self._make_x(...)
        owner = owner_of.get(id(node)) or _owner_by_span(graph, node.lineno)
        maker = _resolve_callable(graph, owner, call.func) if owner else None
        if maker is not None and _returns_jit(graph, maker):
            donate, static, impl = _factory_signature(graph, maker)
            model.bindings[key] = JitBinding(
                key, impl or None, donate, static, node.lineno)

    return model


def _qual_of_def(graph: CallGraph, node: ast.AST) -> Optional[str]:
    for qual, info in graph.functions.items():
        if info.node is node:
            return qual
    return None


def _owner_by_span(graph: CallGraph, lineno: int) -> Optional[str]:
    best: Optional[str] = None
    best_span = None
    for qual, info in graph.functions.items():
        n = info.node
        end = getattr(n, "end_lineno", None) or n.lineno
        if n.lineno <= lineno <= end:
            span = end - n.lineno
            if best_span is None or span < best_span:
                best, best_span = qual, span
    return best


def _returns_jit(graph: CallGraph, qual: str) -> bool:
    info = graph.functions.get(qual)
    if info is None:
        return False
    from tools.pstpu_lint.callgraph import _own_statements

    for node in _own_statements(info.node):
        if (isinstance(node, ast.Return) and isinstance(node.value, ast.Call)
                and _is_tracer_call(node.value) == "jit"):
            return True
    return False


def _factory_signature(graph: CallGraph, qual: str):
    info = graph.functions[qual]
    from tools.pstpu_lint.callgraph import _own_statements

    for node in _own_statements(info.node):
        if (isinstance(node, ast.Return) and isinstance(node.value, ast.Call)
                and _is_tracer_call(node.value) == "jit"):
            donate, dnames, static = _jit_signature(node.value)
            impl = None
            if node.value.args:
                impl = _resolve_callable(graph, qual, node.value.args[0])
            return _donate_positions(graph, impl, donate, dnames), \
                static, impl or ""
    return (), (), ""
