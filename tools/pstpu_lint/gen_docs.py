"""Generate the docs metrics tables and README flag tables from the
single-source registries, inside marker comments:

    <!-- pstpu-metrics:BEGIN <group> -->  ...  <!-- pstpu-metrics:END <group> -->
    <!-- pstpu-flags:BEGIN <tier> -->     ...  <!-- pstpu-flags:END <tier> -->
    <!-- pstpu-wire:BEGIN <group> -->     ...  <!-- pstpu-wire:END <group> -->
    <!-- pstpu-http:BEGIN <group> -->     ...  <!-- pstpu-http:END <group> -->

Write mode refreshes the delimited blocks in place; ``--check`` reports
stale/missing blocks without writing (the PL004 rule runs the metrics half
of the check on every lint; PL010 the wire half; PL011-PL013 the http
tables). Sources of truth:

  * series: tools/pstpu_lint/metrics_registry.py
  * flags:  the argparse definitions in router/parser.py and
            server/api_server.py (tools/pstpu_lint/flags.py scans them)
  * wire:   tools/pstpu_lint/wire_registry.py (docs/WIRE_FORMATS.md)
  * http:   tools/pstpu_lint/http_registry.py (docs/HTTP_PROTOCOL.md,
            plus the focused status table in docs/RESILIENCE.md and the
            resume-header table in docs/ROUTER_SCALE.md)

Usage: ``python -m tools.pstpu_lint.gen_docs [--check]``.
"""

import argparse
import os
import re
import sys
from typing import List, Optional, Tuple

from tools.pstpu_lint import metrics_registry as reg
from tools.pstpu_lint.flags import scan_flags

# docs table group -> file carrying its marker block
TABLES = {
    "catalogue": "docs/METRICS.md",
    "dispatch": "docs/PERF.md",
    "disagg": "docs/DISAGG.md",
    "resilience": "docs/RESILIENCE.md",
    "resume": "docs/RESILIENCE.md",
    "autoscaling": "docs/SOAK.md",
    "kv-economy": "docs/KV_ECONOMY.md",
    "speculative": "docs/PERF.md",
    "multichip": "docs/PERF.md",
    "elastic": "docs/ELASTIC.md",
    "lifecycle": "docs/OBSERVABILITY.md",
    "fleet-perf": "docs/OBSERVABILITY.md",
}

FLAG_TABLES = {
    "router": ("README.md", "production_stack_tpu/router/parser.py"),
    "engine": ("README.md", "production_stack_tpu/server/api_server.py"),
}

# wire table group -> file carrying its marker block (PL010's freshness
# gate, same contract as the PL004 metrics tables above).
WIRE_TABLES = {
    "formats": "docs/WIRE_FORMATS.md",
    "ops": "docs/WIRE_FORMATS.md",
}

# http table group -> file carrying its marker block. The full catalogue
# lives in docs/HTTP_PROTOCOL.md; "status-semantics" and "resume" are the
# focused projections RESILIENCE.md and ROUTER_SCALE.md embed. PL011 owns
# headers/payload/resume freshness, PL012 routes, PL013 the status pair.
HTTP_TABLES = {
    "headers": "docs/HTTP_PROTOCOL.md",
    "routes": "docs/HTTP_PROTOCOL.md",
    "status": "docs/HTTP_PROTOCOL.md",
    "payload": "docs/HTTP_PROTOCOL.md",
    "status-semantics": "docs/RESILIENCE.md",
    "resume": "docs/ROUTER_SCALE.md",
}

_SURFACE_NAMES = {
    reg.ENGINE_TEXT: "engine /metrics",
    reg.ENGINE_COLLECTOR: "engine collector",
    reg.ROUTER: "router /metrics",
}


def render_metrics_table(group: str, registry=None) -> str:
    registry = reg.REGISTRY if registry is None else registry
    lines = [
        "| Series | Type | Labels | Exported by | Meaning |",
        "|---|---|---|---|---|",
    ]
    for s in registry:
        if group not in s.docs:
            continue
        labels = ", ".join(s.labels_for(s.surfaces[0])) or "—"
        exported = ", ".join(_SURFACE_NAMES[x] for x in s.surfaces)
        lines.append(
            f"| `{s.name}` | {s.kind} | {labels} | {exported} "
            f"| {_cell(s.doc)} |"
        )
    return "\n".join(lines)


def _cell(text: str) -> str:
    """Escape raw pipes — inside a markdown table cell they split the row."""
    return text.replace("|", "\\|")


def render_flags_table(parser_source: str) -> str:
    lines = [
        "| Flag | Default | What it does |",
        "|---|---|---|",
    ]
    for flag in scan_flags(parser_source):
        default = flag.default or "—"
        lines.append(
            f"| `{flag.option}` | `{_cell(default)}` | {_cell(flag.help)} |"
        )
    return "\n".join(lines)


def render_wire_table(group: str, formats=None, ops=None) -> str:
    from tools.pstpu_lint import wire_registry as wreg

    formats = wreg.FORMATS if formats is None else formats
    ops = wreg.OPS if ops is None else ops
    if group == "formats":
        lines = [
            "| Magic | Family | Version | Supersedes | Status | Meaning |",
            "|---|---|---|---|---|---|",
        ]
        for f in formats:
            status = "retired" if f.retired else "current"
            lines.append(
                f"| `{f.magic}` | {f.family} | v{f.version} "
                f"| {f.supersedes or '—'} | {status} | {_cell(f.doc)} |"
            )
        return "\n".join(lines)
    lines = [
        "| Op | Name | Batched | Mutates | Native server | Meaning |",
        "|---|---|---|---|---|---|",
    ]
    for o in ops:
        native = "yes" if o.native else "no (STATUS_ERROR; client degrades)"
        lines.append(
            f"| `{o.op}` | {o.name} | {'yes' if o.batched else 'no'} "
            f"| {'yes' if o.mutates else 'no'} | {native} "
            f"| {_cell(o.doc)} |"
        )
    return "\n".join(lines)


def render_http_table(group: str, headers=None, routes=None,
                      statuses=None) -> str:
    from tools.pstpu_lint import http_registry as hreg

    headers = hreg.HEADERS if headers is None else headers
    routes = hreg.ROUTES if routes is None else routes
    statuses = hreg.STATUS_CODES if statuses is None else statuses
    if group == "headers":
        lines = [
            "| Header | Direction | Producers | Consumers | Value "
            "| Status | Meaning |",
            "|---|---|---|---|---|---|---|",
        ]
        for h in headers:
            lines.append(
                f"| `{h.name}` | {h.direction} "
                f"| {', '.join(h.producers)} | {', '.join(h.consumers)} "
                f"| {_cell(h.shape)} "
                f"| {'retired' if h.retired else 'active'} "
                f"| {_cell(h.doc)} |")
        return "\n".join(lines)
    if group == "routes":
        lines = [
            "| Method | Path | Planes | Debug-gated | Internal "
            "| Meaning |",
            "|---|---|---|---|---|---|",
        ]
        for r in routes:
            lines.append(
                f"| {r.method} | `{r.path}` | {', '.join(r.planes)} "
                f"| {'yes' if r.debug else 'no'} "
                f"| {'yes' if r.internal else 'no'} | {_cell(r.doc)} |")
        return "\n".join(lines)
    if group in ("status", "status-semantics"):
        lines = [
            "| Code | Type | Required response headers | Server-emitted "
            "| Meaning |",
            "|---|---|---|---|---|",
        ]
        for s in statuses:
            companions = ", ".join(
                f"`{c}`" for c in s.companions) or "—"
            emitted = "yes" if s.server_emitted else "**never**"
            lines.append(
                f"| {s.code} | `{s.name}` | {companions} | {emitted} "
                f"| {_cell(s.doc)} |")
        return "\n".join(lines)
    if group == "payload":
        lines = [
            "| Key | Type | Meaning |",
            "|---|---|---|",
        ]
        for k in hreg.SSE_PAYLOAD_KEYS:
            lines.append(f"| `{k.key}` | {k.shape} | {_cell(k.doc)} |")
        return "\n".join(lines)
    # "resume": the client->router cross-router resume header pair
    # ROUTER_SCALE.md documents next to the reconnect walkthrough.
    lines = [
        "| Header | Value | Meaning |",
        "|---|---|---|",
    ]
    for h in headers:
        if h.name.startswith("x-pstpu-resume-"):
            lines.append(
                f"| `{h.name}` | {_cell(h.shape)} | {_cell(h.doc)} |")
    return "\n".join(lines)


def _block_re(kind: str, group: str) -> re.Pattern:
    return re.compile(
        rf"(<!-- pstpu-{kind}:BEGIN {re.escape(group)} -->)\n"
        rf"(.*?)"
        rf"(<!-- pstpu-{kind}:END {re.escape(group)} -->)",
        re.S,
    )


def _update_block(text: str, kind: str, group: str,
                  table: str) -> Optional[str]:
    """New file text with the block replaced, or None if markers absent."""
    pat = _block_re(kind, group)
    if pat.search(text) is None:
        return None
    return pat.sub(
        lambda m: m.group(1) + "\n" + table + "\n" + m.group(3),
        text, count=1,
    )


def _iter_blocks(project_root: str, registry=None, kinds=None,
                 wire_registries=None, http_registries=None,
                 http_groups=None):
    """Every generated block as (kind, group, relpath, path, table-or-None);
    table is None when an input file is missing. ``kinds`` restricts which
    table families are rendered (PL004 checks only the metrics tables,
    PL006 only the flag tables — no point rendering the other half);
    ``http_groups`` further restricts the http family (each of
    PL011-PL013 owns a subset of its tables)."""
    if kinds is None or "metrics" in kinds:
        for group, relpath in TABLES.items():
            path = os.path.join(project_root, relpath)
            table = (render_metrics_table(group, registry)
                     if os.path.exists(path) else None)
            yield "metrics", group, relpath, path, table
    if kinds is None or "flags" in kinds:
        for tier, (relpath, parser_rel) in FLAG_TABLES.items():
            path = os.path.join(project_root, relpath)
            parser_path = os.path.join(project_root, parser_rel)
            table = None
            if os.path.exists(path) and os.path.exists(parser_path):
                with open(parser_path, encoding="utf-8") as f:
                    table = render_flags_table(f.read())
            yield "flags", tier, relpath, path, table
    if kinds is None or "wire" in kinds:
        for group, relpath in WIRE_TABLES.items():
            path = os.path.join(project_root, relpath)
            table = (render_wire_table(group, **(wire_registries or {}))
                     if os.path.exists(path) else None)
            yield "wire", group, relpath, path, table
    if kinds is None or "http" in kinds:
        for group, relpath in HTTP_TABLES.items():
            if http_groups is not None and group not in http_groups:
                continue
            path = os.path.join(project_root, relpath)
            table = (render_http_table(group, **(http_registries or {}))
                     if os.path.exists(path) else None)
            yield "http", group, relpath, path, table


def _sync_blocks(project_root: str, registry=None,
                 write: bool = False,
                 kinds=None,
                 wire_registries=None,
                 http_registries=None,
                 http_groups=None) -> List[Tuple[str, str, str]]:
    """One pass over every block. write=False: report (group, relpath,
    problem) per stale/missing block. write=True: refresh stale blocks in
    place and report (group, relpath, "updated") per file written —
    missing files/markers are reported identically in both modes, so
    ``gen_docs`` and ``gen_docs --check`` can never disagree on a tree."""
    out = []
    for kind, group, relpath, path, table in _iter_blocks(
        project_root, registry, kinds, wire_registries,
        http_registries, http_groups
    ):
        if table is None:
            out.append((group, relpath, "missing (file not found)"))
            continue
        with open(path, encoding="utf-8") as f:
            text = f.read()
        updated = _update_block(text, kind, group, table)
        if updated is None:
            out.append((group, relpath, "missing its marker block"))
        elif updated != text:
            if write:
                with open(path, "w", encoding="utf-8") as f:
                    f.write(updated)
                out.append((group, relpath, "updated"))
            else:
                out.append((group, relpath, "out of date"))
    return out


def check_tables(project_root: str,
                 registry=None) -> List[Tuple[str, str, str]]:
    """(group, relpath, problem) for every stale/missing metrics block."""
    return _sync_blocks(project_root, registry, kinds={"metrics"})


def check_flag_tables(project_root: str) -> List[Tuple[str, str, str]]:
    return _sync_blocks(project_root, kinds={"flags"})


def check_wire_tables(project_root: str, formats=None,
                      ops=None) -> List[Tuple[str, str, str]]:
    """(group, relpath, problem) for every stale/missing wire block
    (the PL010 docs-freshness gate)."""
    wire = None
    if formats is not None or ops is not None:
        wire = {"formats": formats, "ops": ops}
    return _sync_blocks(project_root, kinds={"wire"}, wire_registries=wire)


def check_http_tables(project_root: str, groups=None, headers=None,
                      routes=None, statuses=None
                      ) -> List[Tuple[str, str, str]]:
    """(group, relpath, problem) for every stale/missing http block
    (the PL011-PL013 docs-freshness gates; ``groups`` restricts to the
    calling rule's tables)."""
    http = None
    if headers is not None or routes is not None or statuses is not None:
        http = {"headers": headers, "routes": routes,
                "statuses": statuses}
    return _sync_blocks(project_root, kinds={"http"},
                        http_registries=http, http_groups=groups)


def write_tables(project_root: str) -> List[str]:
    """Refresh every block in place; returns the files touched (and raises
    nothing on missing files — they surface via --check / PL004)."""
    return [relpath for _g, relpath, what in _sync_blocks(
        project_root, write=True) if what == "updated"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.pstpu_lint.gen_docs",
        description="Regenerate docs metrics tables + README flag tables "
                    "from the registries.",
    )
    p.add_argument("--check", action="store_true",
                   help="report stale blocks without writing (exit 1)")
    p.add_argument("--project-root", default=".")
    args = p.parse_args(argv)
    root = os.path.abspath(args.project_root)
    if args.check:
        problems = (check_tables(root) + check_flag_tables(root)
                    + check_wire_tables(root) + check_http_tables(root))
        for group, relpath, what in problems:
            print(f"{relpath}: table {group!r} is {what}", file=sys.stderr)
        return 1 if problems else 0
    for relpath in write_tables(root):
        print(f"updated {relpath}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
