"""Canonical registry of every wire format and KV-server op the stack speaks.

Single source of truth for the PL010 wire-protocol-drift rule and for the
generated ``docs/WIRE_FORMATS.md`` tables (``python -m
tools.pstpu_lint.gen_docs``), the same pattern PL004 uses for metrics: the
code, this registry, and the docs can disagree only by failing the lint.

Two planes:

  * **framed formats** — 4-byte magic-tagged envelopes
    (``kv_offload/serde.py``, ``disagg/transfer.py``). The magic IS the
    version tag: a store holding blobs from several generations keeps
    decoding, so every non-retired version needs an encoder AND a decoder
    in-tree, both directions. Quantized payloads additionally namespace
    their STORE KEYS with ``q8|`` so mixed-dtype engines sharing a tier
    never splice incompatible blocks — the namespace literals are
    registered here too and must appear in the code that builds keys.
  * **KV-server ops** — single-byte opcodes of the TCP cache-server
    protocol (``kv_offload/remote.py`` client, ``kv_offload/server.py``
    Python server, ``native/kv_server.cpp``). The native C++ server
    implements a subset and answers ``STATUS_ERROR`` for the rest (the
    client degrades); which ops it covers is recorded per-op so adding an
    op without deciding its native story fails the lint.

To add a format/op: implement both directions, add the entry here, then
run ``python -m tools.pstpu_lint.gen_docs`` to refresh the docs tables.
"""

from dataclasses import dataclass
from typing import Tuple

# Store-key namespaces that partition a shared tier by payload dtype.
# Every namespace must appear in the key-building code (PL010 checks).
KEY_NAMESPACES: Tuple[str, ...] = ("q8|",)


@dataclass(frozen=True)
class WireFormat:
    magic: str           # the 4-byte tag, e.g. "PKV2"
    family: str          # kv-block | chain-envelope | handoff-manifest
    version: int         # lineage within the family
    supersedes: str      # previous magic in the lineage ("" for v1)
    retired: bool        # True = decoders may drop it; no encoder allowed
    doc: str             # one-line meaning for the docs table


@dataclass(frozen=True)
class WireOp:
    op: str              # single byte, e.g. "M"
    name: str
    batched: bool        # carries a packed key list / multi-part response
    mutates: bool        # changes store state (read-only ops must not
                         # refresh LRU recency — the 'I'/'H' contract)
    native: bool         # implemented by native/kv_server.cpp (False =
                         # native answers STATUS_ERROR and the client
                         # degrades to per-key ops / no-op)
    doc: str


FORMATS: Tuple[WireFormat, ...] = (
    WireFormat("PKV1", "kv-block", 1, "", False,
               "KV block, payload only (bf16/f16/f32 pools): header + K + "
               "V bytes. Pre-quantization stores keep decoding."),
    WireFormat("PKV2", "kv-block", 2, "PKV1", False,
               "Quantized KV block (--kv-cache-dtype int8): int8 K/V "
               "payload + per-(slot, head) scale planes; ~0.52x bf16 "
               "bytes, restores bit-identically."),
    WireFormat("PKC1", "chain-envelope", 1, "", False,
               "Prefix-chain envelope wrapping a PKV1/PKV2 blob with the "
               "chain-parent's store key; bare payloads pass through "
               "(chain-unaware servers round-trip it opaquely)."),
    WireFormat("PDX1", "handoff-manifest", 1, "", False,
               "Prefill->decode handoff manifest: JSON header + packed KV "
               "block blobs (delete-after-consume lease)."),
)

OPS: Tuple[WireOp, ...] = (
    WireOp("P", "put", False, True, True,
           "Store one blob under a key (PKC1 envelopes declare the "
           "chain parent)."),
    WireOp("G", "get", False, False, True,
           "Fetch one blob; refreshes its chain's LRU recency."),
    WireOp("E", "exists", False, False, True,
           "Key residency probe (single key)."),
    WireOp("D", "delete", False, True, False,
           "Remove a key — the disagg transfer lease's consume half."),
    WireOp("M", "multi-get", True, False, False,
           "Pipelined batch get: one round trip for a whole restore run."),
    WireOp("I", "index-query", True, False, False,
           "Residency bitmap for a key list; read-only and deliberately "
           "NON-touching so router probes cannot keep cold chains warm."),
    WireOp("H", "hot-chains", False, False, False,
           "Hottest prefix chains root->leaf (prewarm discovery); "
           "read-only like I."),
    WireOp("T", "stats", False, False, True,
           "Server stats as JSON."),
)

MAGICS = tuple(f.magic for f in FORMATS)
OP_CODES = tuple(o.op for o in OPS)


def format_for(magic: str):
    for f in FORMATS:
        if f.magic == magic:
            return f
    return None


def op_for(code: str):
    for o in OPS:
        if o.op == code:
            return o
    return None
