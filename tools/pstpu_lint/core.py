"""Driver: file collection, waiver parsing, rule dispatch, reporting.

Two rule shapes (tools/pstpu_lint/rules/__init__.py registers both):

  * per-file rules — ``fn(relpath, tree, source) -> [Finding]`` run on every
    collected ``.py`` file whose project-relative path matches the rule's
    scope prefixes (scope ``None`` = every file);
  * project rules — ``fn(project_root) -> [Finding]`` run once per
    invocation when their anchor files exist under the project root (the
    metrics-consistency and flag-drift passes need the real tree shape).

Waivers are comments of the form::

    # pstpu-lint: allow[PL001] reason=one-line justification
    # pstpu-lint: allow[PL001,PL003] reason=shared justification

placed on the offending line or alone on the line directly above it. The
waivers themselves are linted (PL000): a waiver with no reason, or one that
no longer suppresses anything, is an error — suppressions never outlive the
finding they justified.
"""

import argparse
import ast
import io
import os
import re
import sys
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

WAIVER_RE = re.compile(
    r"#\s*pstpu-lint:\s*allow\[([A-Za-z0-9_,\s]+)\]\s*(.*)$"
)
REASON_RE = re.compile(r"reason\s*=\s*(\S.*)$")


@dataclass
class Finding:
    rule: str          # e.g. "PL001"
    file: str          # project-relative path (or absolute when outside)
    line: int          # 1-indexed anchor line
    message: str

    def render(self, fmt: str = "text") -> str:
        if fmt == "github":
            # GitHub Actions workflow-command annotation: findings render
            # inline on the PR diff.
            return (f"::error file={self.file},line={self.line},"
                    f"title=pstpu-lint {self.rule}::{self.message}")
        return f"{self.file}:{self.line}: {self.rule} {self.message}"


@dataclass
class Waiver:
    file: str
    anchor_line: int       # the code line this waiver suppresses
    comment_line: int      # where the comment itself sits
    rules: Tuple[str, ...]
    reason: str
    used: set = field(default_factory=set)   # rule codes it suppressed


def parse_waivers(relpath: str, source: str) -> List[Waiver]:
    """Extract waiver comments with their anchor lines.

    A waiver trailing code anchors to the START of that logical line (so a
    trailing comment on a wrapped multi-line call suppresses the finding,
    which is reported at the call's first line); a waiver alone on its
    line anchors to the first line of the next statement.
    """
    # (comment line, text, start line of the logical line it trails or None)
    comments: List[Tuple[int, str, Optional[int]]] = []
    code_lines: set = set()
    logical_start: Optional[int] = None
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.string, logical_start))
            elif tok.type == tokenize.NEWLINE:
                logical_start = None
            elif tok.type not in (
                tokenize.NL, tokenize.INDENT,
                tokenize.DEDENT, tokenize.ENDMARKER, tokenize.ENCODING,
            ):
                if logical_start is None:
                    logical_start = tok.start[0]
                for ln in range(tok.start[0], tok.end[0] + 1):
                    code_lines.add(ln)
    except (tokenize.TokenError, SyntaxError):
        # IndentationError (a SyntaxError subclass) escapes tokenize on
        # dedent mismatches; the ast.parse pass turns the same file into a
        # PL000 "does not parse" finding, so just skip waiver extraction.
        return []

    waivers = []
    for line, text, stmt_start in comments:
        m = WAIVER_RE.search(text)
        if m is None:
            continue
        rules = tuple(
            r.strip().upper() for r in m.group(1).split(",") if r.strip()
        )
        rm = REASON_RE.search(m.group(2))
        reason = rm.group(1).strip() if rm else ""
        if stmt_start is not None:
            anchor = stmt_start
        else:
            following = [ln for ln in code_lines if ln > line]
            anchor = min(following) if following else line
        waivers.append(Waiver(
            file=relpath, anchor_line=anchor, comment_line=line,
            rules=rules, reason=reason,
        ))
    return waivers


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted list of .py files."""
    skip_dirs = {"__pycache__", ".git", ".venv", "node_modules", ".mypy_cache",
                 ".ruff_cache", ".pytest_cache"}
    out = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                out.append(os.path.abspath(path))
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in skip_dirs)
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.abspath(os.path.join(root, name)))
        else:
            raise FileNotFoundError(path)
    return sorted(set(out))


def _relpath(path: str, project_root: str) -> str:
    rel = os.path.relpath(path, project_root)
    return rel.replace(os.sep, "/")


def _in_scope(relpath: str, scopes: Optional[Tuple[str, ...]]) -> bool:
    if scopes is None:
        return True
    return any(
        relpath == s or relpath.startswith(s.rstrip("/") + "/")
        for s in scopes
    )


def default_project_root() -> str:
    """The repo that owns this tools package — NOT the cwd. Scoped rules
    match project-relative paths like 'production_stack_tpu/router/...';
    anchoring to cwd would make `cd production_stack_tpu && python -m
    tools.pstpu_lint server/` silently disable most rules and exit 0
    falsely clean."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_lint(
    paths: Sequence[str],
    project_root: Optional[str] = None,
    project_rules: bool = True,
) -> List[Finding]:
    """Lint ``paths``; returns the surviving findings (waivers applied),
    including PL000 waiver-hygiene findings."""
    from tools.pstpu_lint import rules as rules_mod

    project_root = os.path.abspath(project_root or default_project_root())
    files = collect_files(paths)
    findings: List[Finding] = []
    waivers: List[Waiver] = []

    for path in files:
        relpath = _relpath(path, project_root)
        with open(path, encoding="utf-8") as f:
            source = f.read()
        waivers.extend(parse_waivers(relpath, source))
        try:
            tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            findings.append(Finding(
                "PL000", relpath, e.lineno or 1,
                f"file does not parse: {e.msg}",
            ))
            continue
        for code, scopes, fn in rules_mod.FILE_RULES:
            if _in_scope(relpath, scopes):
                findings.extend(fn(relpath, tree, source))

    if project_rules:
        for code, wants, fn in rules_mod.PROJECT_RULES:
            if wants(project_root):
                findings.extend(fn(project_root))

    # ---------------------------------------------------------- apply waivers
    by_anchor: Dict[Tuple[str, int], List[Waiver]] = {}
    for w in waivers:
        by_anchor.setdefault((w.file, w.anchor_line), []).append(w)

    surviving = []
    for f in findings:
        waived = False
        for w in by_anchor.get((f.file, f.line), []):
            if f.rule in w.rules:
                w.used.add(f.rule)
                waived = True
        if not waived:
            surviving.append(f)

    # ------------------------------------------------------- waiver hygiene
    known_rules = {"PL000"}
    known_rules.update(code for code, _s, _f in rules_mod.FILE_RULES)
    known_rules.update(code for code, _w, _f in rules_mod.PROJECT_RULES)
    for w in waivers:
        if not w.reason:
            surviving.append(Finding(
                "PL000", w.file, w.comment_line,
                f"waiver allow[{','.join(w.rules)}] has no reason= "
                f"justification",
            ))
        # A waiver naming a rule that does not exist (typo, or a code left
        # behind by a rename) would otherwise sit forever looking
        # load-bearing while suppressing nothing.
        unknown = [r for r in w.rules if r not in known_rules]
        if unknown:
            surviving.append(Finding(
                "PL000", w.file, w.comment_line,
                f"waiver allow[{','.join(unknown)}] names unknown rule "
                f"code(s) — known: {', '.join(sorted(known_rules))}",
            ))
        stale = [r for r in w.rules
                 if r not in w.used and r not in unknown]
        if stale and w.reason:
            surviving.append(Finding(
                "PL000", w.file, w.comment_line,
                f"waiver allow[{','.join(stale)}] suppresses nothing "
                f"(line {w.anchor_line}) — remove it",
            ))

    surviving.sort(key=lambda f: (f.file, f.line, f.rule))
    return surviving


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.pstpu_lint",
        description="Concurrency- and invariant-checking static analysis "
                    "for the production-stack-tpu serving stack "
                    "(docs/LINTING.md has the rule catalogue).",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to lint (default: "
                             "production_stack_tpu tools benchmarks under "
                             "the project root)")
    parser.add_argument("--format", choices=["text", "github"],
                        default="text",
                        help="'github' emits ::error workflow-command "
                             "annotations for inline PR rendering")
    parser.add_argument("--project-root", default=None,
                        help="root the per-rule path scopes and project "
                             "rules resolve against (default: the repo "
                             "containing tools/pstpu_lint, so running from "
                             "a subdirectory cannot silently disable "
                             "scoped rules)")
    parser.add_argument("--no-project-rules", action="store_true",
                        help="skip the repo-level passes (PL004 metrics "
                             "consistency, PL006 flag drift)")
    args = parser.parse_args(argv)

    root = os.path.abspath(args.project_root or default_project_root())
    paths = args.paths or [
        os.path.join(root, p)
        for p in ("production_stack_tpu", "tools", "benchmarks")
        if os.path.exists(os.path.join(root, p))
    ]
    try:
        findings = run_lint(
            paths, project_root=args.project_root,
            project_rules=not args.no_project_rules,
        )
    except FileNotFoundError as e:
        print(f"pstpu-lint: no such path: {e}", file=sys.stderr)
        return 2

    for f in findings:
        print(f.render(args.format))
    if findings:
        print(f"pstpu-lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0
