"""Single source of truth for the stack's HTTP control surface.

The router, the engine API server, the fake engine the resilience/soak
harness runs against, and the bench clients speak a private protocol on
top of the OpenAI surface: ``x-pstpu-*``/``x-slo-*``/``x-ttft-*``/
``x-request-*`` headers, internal routes (``/disagg/prefill``,
``/prewarm``, ``/debug/*``, ``/fleet``), shed-vs-error status semantics,
and the ``pstpu`` SSE chunk payload the cross-router resume protocol
deserializes. This module is the canonical catalogue; the PL011 (header
drift), PL012 (route drift) and PL013 (status-code semantics) rules in
``rules/http_drift.py`` check the tree against it both directions, and
``gen_docs`` renders docs/HTTP_PROTOCOL.md plus the focused tables in
docs/RESILIENCE.md and docs/ROUTER_SCALE.md from it.

Planes:

  * ``router``   — production_stack_tpu/router/
  * ``engine``   — production_stack_tpu/ outside the router tier (the API
                   server, disagg, engine internals)
  * ``fake``     — tests/fake_engine.py (the harness engine; its contract
                   must track the real engine's — PL012's parity leg)
  * ``bench``    — benchmarks/ (the load/soak clients)
  * ``external`` — real API clients outside this repo; listing it means
                   no in-repo site is required for that side.
"""

from dataclasses import dataclass
from typing import Optional, Tuple

# Planes whose source the drift rules actually scan. "external" is
# documentation-only: a producers/consumers entry naming it promises
# nothing the linter can check.
SCANNED_PLANES = ("router", "engine", "fake", "bench")


@dataclass(frozen=True)
class ProtocolHeader:
    name: str                 # canonical lowercase wire name
    direction: str            # "request" | "response" | "both"
    producers: Tuple[str, ...]  # planes that set the header
    consumers: Tuple[str, ...]  # planes that read it
    shape: str                # value shape, for the docs table
    retired: bool             # True: literal may linger in comments only
    doc: str


# Every protocol header on the wire. PL011 enforces, per scanned plane
# listed: >=1 producing site (dict-literal key / headers[h] = ...) for
# each producer plane and >=1 consuming site (.get/.pop/`in`) for each
# consumer plane — a header set by the router but read nowhere on the
# engine is drift, and vice versa. Literals must be lowercase (aiohttp
# lookups are case-insensitive, greps are not).
HEADERS: Tuple[ProtocolHeader, ...] = (
    ProtocolHeader(
        "x-request-id", "both", ("external", "router", "engine"),
        ("router", "engine", "bench"),
        "opaque request id (minted router-side when absent; echoed on "
        "the response)", False,
        "End-to-end correlation id: names request-monitor entries, "
        "flight-recorder timelines, and the soak anomaly dump.",
    ),
    ProtocolHeader(
        "x-request-timeout", "request", ("external",), ("router",),
        "seconds (float; may only tighten --default-timeout)", False,
        "Per-request total budget override, measured from router ingress.",
    ),
    ProtocolHeader(
        "x-ttft-deadline", "request", ("external", "bench"), ("router",),
        "seconds (float; may only tighten --default-ttft-deadline)", False,
        "Per-request budget to the first backend byte; expiry is a 504 "
        "with kind=ttft.",
    ),
    ProtocolHeader(
        "x-slo-class", "request", ("external", "bench"), ("router",),
        "SLO class name (e.g. interactive, batch)", False,
        "Labels the request for router_slo_attainment tracking and the "
        "soak report's per-class accounting.",
    ),
    ProtocolHeader(
        "x-slo-ttft", "request", ("external", "bench"), ("router",),
        "seconds (float; soft target, no enforcement)", False,
        "Soft TTFT target the attainment fraction is computed against "
        "(docs/SOAK.md); never aborts the request.",
    ),
    ProtocolHeader(
        "x-pstpu-resume", "request", ("router",), ("engine", "fake"),
        '"1"', False,
        "Router->engine stream opt-in: attach the per-chunk pstpu resume "
        "payload. Direct API clients get pristine OpenAI chunks.",
    ),
    ProtocolHeader(
        "x-pstpu-resume-tokens", "request", ("external", "bench"),
        ("router",),
        "comma-separated output token ids", False,
        "Client->router cross-router resume: the output ids the client "
        "already holds; the peer replica splices the continuation "
        "(docs/ROUTER_SCALE.md).",
    ),
    ProtocolHeader(
        "x-pstpu-resume-seed", "request", ("external", "bench"),
        ("router",),
        "integer (the pstpu payload's seed)", False,
        "Client->router cross-router resume: the resolved sampler seed "
        "base, required for a token-identical seeded continuation.",
    ),
    ProtocolHeader(
        "x-pstpu-disagg", "request", ("router",), ("engine",),
        '"decode" (hop marker)', False,
        "Marks the decode hop of the two-hop disagg flow; the decode-role "
        "gate rejects generation requests without it.",
    ),
    ProtocolHeader(
        "x-pstpu-transfer-key", "request", ("router",), ("engine",),
        "KV-store key of the prefill handoff bundle", False,
        "Where the decode engine fetches the prefill's KV handoff "
        "manifest from the shared tier.",
    ),
    ProtocolHeader(
        "x-pstpu-endpoint", "request", ("router",), ("engine",),
        '"chat" | "completions"', False,
        "Which OpenAI surface the decode hop must answer in — the hop is "
        "always POSTed to /v1/completions internally.",
    ),
    ProtocolHeader(
        "x-pstpu-disagg-fallback", "request", ("router",), ("engine",),
        '"1"', False,
        "Marks continuation/fallback traffic that must be servable "
        "end-to-end on ANY role; unified engines ignore it, prefill/"
        "decode role gates stand down.",
    ),
)

# Lowercase header-name prefixes that may legitimately appear as bare
# literals (forward/strip-by-namespace sites in the proxy path). A
# literal exactly equal to one of these is a namespace filter, not an
# unregistered header.
HEADER_NAMESPACES = ("x-pstpu-",)

# Prefixes PL011 claims: any string literal in the scanned planes that
# looks like one of these MUST resolve to a HEADERS entry (or a
# namespace filter above).
CLAIMED_PREFIXES = ("x-pstpu-", "x-slo-", "x-ttft-", "x-request-")


@dataclass(frozen=True)
class Route:
    method: str               # "GET" | "POST" | ...
    path: str                 # aiohttp route pattern, {param} syntax
    planes: Tuple[str, ...]   # planes that must register it
    debug: bool               # must sit behind config.debug_endpoints
    internal: bool            # plane-to-plane hop: exempt from the
    #                           test-reference requirement
    test_ref: Optional[str]   # literal the test scan greps for (None:
    #                           the path itself)
    doc: str


# Every HTTP route the three servers register. PL012 enforces: every
# observed add_get/add_post is registered here for its plane and vice
# versa; debug-gating matches; every non-internal route is referenced by
# at least one file under tests/.
ROUTES: Tuple[Route, ...] = (
    Route("POST", "/v1/chat/completions", ("router", "engine", "fake"),
          False, False, None, "OpenAI chat surface (streams via SSE)."),
    Route("POST", "/v1/completions", ("router", "engine", "fake"),
          False, False, None, "OpenAI completions surface."),
    Route("POST", "/v1/embeddings", ("router", "engine", "fake"),
          False, False, None, "OpenAI embeddings surface."),
    Route("POST", "/v1/rerank", ("router", "engine", "fake"),
          False, False, None, "Rerank surface (Jina/Cohere shape)."),
    Route("POST", "/rerank", ("engine", "fake"), False, False, None,
          "Engine-level alias of /v1/rerank (vLLM compat; the router "
          "serves only the /v1 name)."),
    Route("GET", "/v1/models", ("router", "engine", "fake"),
          False, False, None,
          "Model listing; the discovery probe's readiness signal."),
    Route("GET", "/health", ("router", "engine", "fake"),
          False, False, None,
          "Readiness: 200 serving / 503 + Retry-After while draining or "
          "degraded."),
    Route("GET", "/metrics", ("router", "engine", "fake"),
          False, False, None, "Prometheus exposition (PL004's surface)."),
    Route("GET", "/prefix_index", ("engine", "fake"), False, False, None,
          "Prefix-cache block index the router's prefix-aware routing "
          "scores against."),
    Route("POST", "/prewarm", ("engine", "fake"), False, False, None,
          "Prompt prewarm push (router initialize_all fan-out)."),
    Route("GET", "/version", ("engine", "fake"), False, False, None,
          "Build/schema versions for mixed-fleet rollout checks."),
    Route("POST", "/disagg/prefill", ("engine",), False, True, None,
          "Internal router->engine hop 1 of the disagg flow; never "
          "client-facing."),
    Route("GET", "/debug/requests/{request_id}", ("engine",), True, False,
          "/debug/requests", "Flight-recorder per-request timeline."),
    Route("GET", "/debug/timeline", ("engine",), True, False, None,
          "Flight-recorder recent-request ring."),
    Route("POST", "/debug/profile", ("engine",), True, False,
          "/debug/profile", "Start a bounded device-profiler capture "
          "(409 while one is running)."),
    Route("GET", "/debug/profile", ("engine",), True, False,
          "/debug/profile", "Profiler capture status."),
    Route("GET", "/fleet", ("router",), False, False, None,
          "Fleet-wide live perf rollup (docs/OBSERVABILITY.md)."),
    Route("POST", "/v1/files", ("router",), False, False, None,
          "Files API upload (501 unless --enable-files-api)."),
    Route("GET", "/v1/files/{file_id}", ("router",), False, False,
          "/v1/files", "Files API metadata."),
    Route("GET", "/v1/files/{file_id}/content", ("router",), False, False,
          "/v1/files", "Files API content download."),
    Route("POST", "/v1/batches", ("router",), False, False, None,
          "Batch API create (501 unless --enable-batch-api)."),
    Route("GET", "/v1/batches", ("router",), False, False, None,
          "Batch API list."),
    Route("GET", "/v1/batches/{batch_id}", ("router",), False, False,
          "/v1/batches", "Batch API status."),
    Route("POST", "/v1/batches/{batch_id}/cancel", ("router",), False,
          False, "/v1/batches", "Batch API cancel."),
    Route("POST", "/fault", ("fake",), False, False, None,
          "Fault-injection control surface of the harness engine only; "
          "real engines 404 it."),
)


@dataclass(frozen=True)
class StatusCode:
    code: int
    name: str                 # the error payload's "type"
    companions: Tuple[str, ...]  # response headers every emit site must
    #                              carry (lowercase)
    server_emitted: bool      # False: client-side marker, a server emit
    #                           site is always a finding
    doc: str


# 4xx/5xx semantics. PL013 enforces: every constant-status emit site in
# the server planes uses a registered code, carries the registry's
# companion headers, and never emits a client-side marker code.
STATUS_CODES: Tuple[StatusCode, ...] = (
    StatusCode(400, "invalid_request_error", (), True,
               "Malformed body/params; also malformed cross-router "
               "resume headers (reconnect without them to restart)."),
    StatusCode(401, "unauthorized", (), True,
               "Missing/invalid API key when --api-key is set."),
    StatusCode(404, "not_found", (), True,
               "Unknown model, unknown debug handle, or a disabled "
               "debug surface."),
    StatusCode(409, "conflict", (), True,
               "Profiler busy: one bounded capture at a time."),
    StatusCode(501, "not_implemented", (), True,
               "Feature disabled by role/flags (disagg on a unified "
               "deployment, files/batch API off)."),
    StatusCode(502, "bad_gateway", (), True,
               "Retry budget exhausted on backend transport failures; "
               "carries the last failure."),
    StatusCode(503, "service_unavailable", ("retry-after",), True,
               "Intentional shed (drain, queue bound, breaker open, "
               "role gate, handoff unavailable) or not-ready health. "
               "ALWAYS carries Retry-After — clients and the soak "
               "accounting distinguish shed from failure by it."),
    StatusCode(504, "deadline_exceeded", (), True,
               "TTFT or total budget expired before/while streaming "
               "(kind labels the metric)."),
    StatusCode(599, "client_transport_error", (), False,
               "Bench-client marker for transport failures and "
               "mid-stream truncations; never emitted by a server."),
)

_STATUS_BY_CODE = {s.code: s for s in STATUS_CODES}
_HEADERS_BY_NAME = {h.name: h for h in HEADERS}


def header_for(name: str) -> Optional[ProtocolHeader]:
    return _HEADERS_BY_NAME.get(name.lower())


def status_for(code: int) -> Optional[StatusCode]:
    return _STATUS_BY_CODE.get(code)


@dataclass(frozen=True)
class PayloadKey:
    key: str
    shape: str
    doc: str


# The `pstpu` SSE chunk payload (docs/RESILIENCE.md): the state channel
# cross-router resume is built on. PL011 checks every emitter/consumer
# file speaks exactly these keys.
SSE_PAYLOAD_FIELD = "pstpu"
SSE_PAYLOAD_KEYS: Tuple[PayloadKey, ...] = (
    PayloadKey("toks", "list[int]",
               "Output token ids carried by this chunk."),
    PayloadKey("off", "int",
               "Offset of toks[0] in the full output (dedupes overlap "
               "on splice)."),
    PayloadKey("seed", "int",
               "Resolved sampler seed base; rides the wire so a "
               "cross-engine resume of an unseeded request stays "
               "deterministic."),
)

# Files that emit / parse the payload; each must mention the field name
# and every key as a string literal.
SSE_PAYLOAD_EMITTERS = (
    "production_stack_tpu/server/api_server.py",
    "tests/fake_engine.py",
)
SSE_PAYLOAD_CONSUMERS = (
    "production_stack_tpu/router/sse.py",
    "benchmarks/multi_round_qa.py",
)
