"""Canonical registry of every Prometheus series the stack exports.

This is the single source of truth the PL004 metrics-drift rule checks the
code against, and the input ``tools.pstpu_lint.gen_docs`` renders the docs
metrics tables from. Three exporter surfaces:

  * ``engine-text``      — the engine pod's hand-rolled /metrics renderer
                           (production_stack_tpu/server/metrics.py, plus the
                           histogram names in engine/metrics.py it renders);
  * ``engine-collector`` — the prometheus_client Collector alternative
                           (production_stack_tpu/engine/metrics.py);
  * ``router``           — the router's prometheus_client module registry
                           (production_stack_tpu/router/metrics.py).

Naming convention: ``pstpu:`` for series this stack introduces, ``router_``
for router data-plane outcomes, ``vllm:`` for the scraper/dashboard
compatibility contract (the reference Grafana dashboard and the router's
EngineStatsScraper parse these exact names — do NOT rename them).

The two engine surfaces are parallel renderers of the same stats and MUST
agree on names and label sets wherever both render a series; PL004 enforces
that, and enforces that this file, the renderers, and the docs tables never
drift from each other. To add a series: emit it in the renderer(s), add a
``Series`` entry here, then run ``python -m tools.pstpu_lint.gen_docs`` to
refresh the docs tables.
"""

from dataclasses import dataclass, field
from typing import Dict, Tuple

ALLOWED_PREFIXES = ("pstpu:", "router_", "vllm:")

ENGINE_TEXT = "engine-text"
ENGINE_COLLECTOR = "engine-collector"
ROUTER = "router"


@dataclass(frozen=True)
class Series:
    name: str
    kind: str                       # gauge | counter | histogram
    labels: Tuple[str, ...]         # label names on the engine surfaces
    surfaces: Tuple[str, ...]       # which exporters render it
    docs: Tuple[str, ...]           # docs table groups (gen_docs.TABLES)
    doc: str                        # one-line meaning for the docs tables
    # Router re-exports per-engine series under its own label set (the
    # scraper relabels by backend); only set for the "router" surface.
    router_labels: Tuple[str, ...] = field(default=())

    def labels_for(self, surface: str) -> Tuple[str, ...]:
        return self.router_labels if surface == ROUTER else self.labels


_BOTH_ENGINE = (ENGINE_TEXT, ENGINE_COLLECTOR)

REGISTRY: Tuple[Series, ...] = (
    # ------------------------------------------------ engine: vllm compat
    Series("vllm:num_requests_running", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "Requests currently decoding"),
    Series("vllm:num_requests_waiting", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "Requests waiting for prefill"),
    Series("vllm:gpu_cache_usage_perc", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "KV-pool usage fraction (TPU HBM)"),
    Series("vllm:gpu_prefix_cache_hits_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "Prefix-cache hit tokens"),
    Series("vllm:gpu_prefix_cache_queries_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "Prefix-cache queried tokens"),
    Series("vllm:num_preemptions_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "Sequences preempted"),
    Series("vllm:prompt_tokens_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "Prefilled tokens"),
    Series("vllm:generation_tokens_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "Generated tokens"),
    Series("vllm:time_to_first_token_seconds", "histogram", ("model_name",),
           (ENGINE_TEXT,), ("catalogue",),
           "TTFT distribution (vLLM bucket boundaries)"),
    Series("vllm:e2e_request_latency_seconds", "histogram", ("model_name",),
           (ENGINE_TEXT,), ("catalogue",),
           "End-to-end request latency distribution"),
    # ------------------------------------------------ engine: pstpu series
    Series("pstpu:engine_uptime_seconds", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "Engine uptime"),
    Series("pstpu:kv_offload_blocks", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue",),
           "KV blocks resident in the host offload pool"),
    Series("pstpu:queue_depth", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "autoscaling"),
           "Engine backlog (running + waiting requests) — the per-pod "
           "HPA metric"),
    Series("pstpu:decode_dispatches_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "dispatch"),
           "Fused decode dispatches issued"),
    Series("pstpu:prefill_dispatches_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "dispatch"),
           "Prefill chunk dispatches issued"),
    Series("pstpu:dispatch_overlap_ratio", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "dispatch"),
           "Fraction of dispatch fetches with another dispatch outstanding"),
    Series("pstpu:dispatch_gap_seconds_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "dispatch"),
           "Host-observed seconds with no dispatch outstanding "
           "(pipeline bubble)"),
    Series("pstpu:kv_cache_dtype", "gauge", ("model_name", "kv_cache_dtype"),
           _BOTH_ENGINE, ("catalogue", "dispatch"),
           "KV-cache storage dtype of the block pool (1 = active)"),
    Series("pstpu:kv_quant_bytes_saved_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "dispatch"),
           "KV-pool bytes the quantized cache avoided writing vs the "
           "compute dtype"),
    # ------------------------------------------- engine: KV economy
    Series("pstpu:prefix_index_size", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "kv-economy"),
           "Content-addressed blocks resident in the device prefix cache "
           "(the /prefix_index digest size)"),
    Series("pstpu:kv_restore_saved_tokens_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "kv-economy"),
           "Prompt tokens restored from the shared KV tier instead of "
           "recomputed (cost-model admitted)"),
    Series("pstpu:kv_shared_tier_hits_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "kv-economy"),
           "KV blocks served by the shared host/remote tiers during "
           "prefill restores"),
    Series("pstpu:kv_shared_tier_misses_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "kv-economy"),
           "Restore-candidate KV blocks the shared tiers did not hold"),
    Series("pstpu:kv_chain_evictions_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "kv-economy"),
           "Leaf-first chain evictions in the local host KV tier"),
    # --------------------------------------------- engine: multichip
    Series("pstpu:mesh_tp_size", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "multichip"),
           "Tensor-parallel degree of the serving mesh"),
    Series("pstpu:mesh_sp_size", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "multichip"),
           "Sequence-parallel degree of the serving mesh"),
    Series("pstpu:mesh_devices", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "multichip"),
           "Devices the serving mesh occupies (dp x sp x tp)"),
    Series("pstpu:hbm_kv_bytes", "gauge", ("model_name", "device"),
           _BOTH_ENGINE, ("catalogue", "multichip"),
           "KV-pool bytes resident per mesh device (payload + scale "
           "sidecars; kv-head-sharded at tp>1)"),
    # --------------------------------------------- engine: speculative
    Series("pstpu:spec_enabled", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "speculative"),
           "Speculative decoding active (--speculative-num-tokens > 0)"),
    Series("pstpu:spec_draft_tokens_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "speculative"),
           "Draft-model token proposals made inside fused decode "
           "dispatches"),
    Series("pstpu:spec_accepted_tokens_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "speculative"),
           "Draft proposals that survived target verification (bonus "
           "tokens not counted)"),
    Series("pstpu:spec_acceptance_rate", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "speculative"),
           "Lifetime fraction of draft proposals accepted by the target"),
    Series("pstpu:spec_acceptance_rate_window", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "speculative"),
           "Draft acceptance over the last <=64 dispatch fetches "
           "(windowed companion to the lifetime rate)"),
    Series("pstpu:spec_draft_depth", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "speculative"),
           "Mean served draft depth per live verify cycle (adaptive "
           "gamma controller)"),
    Series("pstpu:spec_tree_nodes_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "speculative"),
           "Token-tree nodes verified (tree speculation)"),
    Series("pstpu:spec_acceptance_ema", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "speculative"),
           "Mean per-sequence acceptance EMA over live sequences "
           "(adaptive controller)"),
    Series("pstpu:spec_gamma0_dispatches_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "speculative"),
           "Decode dispatches the adaptive controller degraded to the "
           "plain (non-speculative) scan"),
    # --------------------------------------------- engine: elastic fast-start
    Series("pstpu:startup_weight_load_seconds", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "elastic"),
           "Seconds loading model weights at startup (overlaps compile "
           "with overlap_weight_load)"),
    Series("pstpu:startup_compile_seconds", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "elastic"),
           "Seconds in the AOT compile-only warmup prepass (overlapped "
           "with the weight load)"),
    Series("pstpu:startup_warmup_seconds", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "elastic"),
           "Seconds executing warmup shape families before serving"),
    Series("pstpu:startup_prewarm_seconds", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "elastic"),
           "Seconds serving POST /prewarm hot-chain pulls from the shared "
           "KV tier"),
    Series("pstpu:startup_total_seconds", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "elastic"),
           "Engine construction to ready-to-serve, seconds"),
    Series("pstpu:startup_cache_hit_families", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "elastic"),
           "Warmup variants loaded from the persistent compile cache "
           "(no recompile)"),
    Series("pstpu:startup_cache_miss_families", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "elastic"),
           "Warmup variants that compiled from scratch (cold cache or "
           "changed config)"),
    # ------------------------------------------ engine: request lifecycle
    # (docs/OBSERVABILITY.md): per-phase latency split — where a request's
    # TTFT went — plus tracing exporter hygiene.
    Series("pstpu:queue_wait_seconds", "histogram", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "lifecycle"),
           "Arrival to first dispatch issue per request (queue wait)"),
    Series("pstpu:prefill_seconds", "histogram", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "lifecycle"),
           "First prefill issue to final prefill chunk fetch per request"),
    Series("pstpu:decode_train_seconds", "histogram", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "lifecycle"),
           "Issue-to-fetch duration of each fused decode dispatch (train)"),
    Series("pstpu:restore_round_trip_seconds", "histogram", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "lifecycle"),
           "Duration of each shared-tier I/M restore round trip that "
           "restored KV blocks"),
    Series("pstpu:trace_spans_dropped_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "lifecycle"),
           "OTLP spans dropped because the exporter queue was full"),
    # --------------------------------------------- engine: mid-stream resume
    Series("pstpu:resume_restored_tokens_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "resume"),
           "Prompt+resume tokens served from the prefix cache or KV tiers "
           "on mid-stream resume requests instead of recomputed"),
    Series("pstpu:disagg_role", "gauge", ("model_name", "role"),
           _BOTH_ENGINE, ("catalogue", "disagg"),
           "Engine disaggregation role (1 = active)"),
    Series("pstpu:kv_handoffs_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "disagg"),
           "Completed KV handoff transfers (published or consumed)"),
    Series("pstpu:kv_handoff_bytes_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "disagg"),
           "Bytes moved through the KV handoff plane"),
    Series("pstpu:kv_handoff_seconds_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "disagg"),
           "Seconds serializing/publishing/consuming KV handoffs"),
    Series("pstpu:kv_handoff_failures_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "disagg"),
           "Failed KV handoff transfers"),
    # --------------------------------------------- router: vllm re-exports
    Series("vllm:num_requests_running", "gauge", ("model_name",),
           (ROUTER,), ("catalogue",),
           "Running requests per engine (router view)",
           router_labels=("server",)),
    Series("vllm:num_requests_waiting", "gauge", ("model_name",),
           (ROUTER,), ("catalogue",),
           "Waiting requests per engine (router view)",
           router_labels=("server",)),
    Series("vllm:gpu_cache_usage_perc", "gauge", ("model_name",),
           (ROUTER,), ("catalogue",),
           "KV-pool usage per engine (router view)",
           router_labels=("server",)),
    Series("vllm:current_qps", "gauge", (), (ROUTER,), ("catalogue",),
           "Router-observed QPS per engine", router_labels=("server",)),
    Series("vllm:avg_decoding_length", "gauge", (), (ROUTER,), ("catalogue",),
           "Average decoding length per engine", router_labels=("server",)),
    Series("vllm:num_prefill_requests", "gauge", (), (ROUTER,),
           ("catalogue",),
           "In-prefill requests per engine", router_labels=("server",)),
    Series("vllm:num_decoding_requests", "gauge", (), (ROUTER,),
           ("catalogue",),
           "In-decode requests per engine", router_labels=("server",)),
    Series("vllm:healthy_pods_total", "gauge", (), (ROUTER,), ("catalogue",),
           "Healthy engine pods", router_labels=("server",)),
    Series("vllm:avg_latency", "gauge", (), (ROUTER,), ("catalogue",),
           "Average end-to-end latency per engine",
           router_labels=("server",)),
    Series("vllm:avg_itl", "gauge", (), (ROUTER,), ("catalogue",),
           "Average inter-token latency per engine",
           router_labels=("server",)),
    Series("vllm:num_requests_swapped", "gauge", (), (ROUTER,),
           ("catalogue",),
           "Swapped-out requests per engine", router_labels=("server",)),
    Series("vllm:gpu_prefix_cache_hit_rate", "gauge", (), (ROUTER,),
           ("catalogue",),
           "Per-interval prefix-cache hit rate per engine",
           router_labels=("server",)),
    Series("vllm:router_queueing_delay_seconds", "gauge", (), (ROUTER,),
           ("catalogue",),
           "Router-side queueing delay (route decision to backend connect)",
           router_labels=("server",)),
    Series("vllm:router_ttft_seconds", "histogram", (), (ROUTER,),
           ("catalogue",),
           "Router-observed TTFT distribution", router_labels=("server",)),
    Series("vllm:router_e2e_latency_seconds", "histogram", (), (ROUTER,),
           ("catalogue",),
           "Router-observed end-to-end latency distribution",
           router_labels=("server",)),
    Series("vllm:avg_prefill_length", "gauge", (), (ROUTER,), ("catalogue",),
           "Average prompt length per engine", router_labels=("server",)),
    # ------------------------------------------------ router: data plane
    Series("router_retries_total", "counter", (), (ROUTER,),
           ("catalogue", "resilience"),
           "Pre-stream backend failures that triggered a retry",
           router_labels=("server",)),
    Series("router_failovers_total", "counter", (), (ROUTER,),
           ("catalogue", "resilience"),
           "Retries that moved the request away from this backend",
           router_labels=("server",)),
    Series("router_circuit_state", "gauge", (), (ROUTER,),
           ("catalogue", "resilience"),
           "Circuit breaker state (0 closed / 1 open / 2 half-open); "
           "router identifies the observing replica",
           router_labels=("server", "router")),
    Series("router_deadline_exceeded_total", "counter", (), (ROUTER,),
           ("catalogue", "resilience"),
           "Deadline aborts (kind: ttft or total)",
           router_labels=("server", "kind")),
    # ------------------------------------------- router: mid-stream resume
    Series("router_midstream_resumes_total", "counter", (), (ROUTER,),
           ("catalogue", "resume"),
           "Mid-stream backend failures the router tried to resume on "
           "another backend (outcome: resumed = continuation spliced, "
           "failed = no backend could attach, peer = client reconnected "
           "here after losing another router replica)",
           router_labels=("outcome",)),
    Series("router_truncations_total", "counter", (), (ROUTER,),
           ("catalogue", "resume"),
           "Client streams that ended without data: [DONE] (mid-stream "
           "failure not resumed, resume budget exhausted, or mid-stream "
           "deadline)",
           router_labels=()),
    Series("router_trace_spans_dropped_total", "counter", (), (ROUTER,),
           ("catalogue", "lifecycle"),
           "OTLP spans the router's exporter queue had to drop",
           router_labels=()),
    # ------------------------------------------------ router: autoscaling
    Series("router_queue_depth", "gauge", (), (ROUTER,),
           ("catalogue", "autoscaling"),
           "Engine-reported running+waiting requests per backend "
           "(queue-depth scale-up signal)",
           router_labels=("server",)),
    Series("router_kv_pressure", "gauge", (), (ROUTER,),
           ("catalogue", "autoscaling"),
           "KV-pool usage fraction per backend (HBM pressure)",
           router_labels=("server",)),
    Series("router_pool_utilization", "gauge", (), (ROUTER,),
           ("catalogue", "autoscaling"),
           "Mean in-flight depth per engine in each disagg role pool",
           router_labels=("role",)),
    Series("router_slo_attainment", "gauge", (), (ROUTER,),
           ("catalogue", "autoscaling"),
           "Rolling-window fraction of x-slo-class requests meeting their "
           "soft TTFT target",
           router_labels=("slo_class",)),
    # ------------------------------------------------ router: KV economy
    Series("router_backend_kv_hit_rate", "gauge", (), (ROUTER,),
           ("catalogue", "kv-economy"),
           "Per-interval prefix-cache hit rate per backend (scrape plane)",
           router_labels=("server",)),
    Series("router_prefix_index_entries", "gauge", (), (ROUTER,),
           ("catalogue", "kv-economy"),
           "Entries in the backend's last scraped /prefix_index digest",
           router_labels=("server",)),
    Series("router_disagg_handoffs_total", "counter", (), (ROUTER,),
           ("catalogue", "disagg"),
           "Prefill->decode handoffs completed through the two-hop flow",
           router_labels=()),
    Series("router_disagg_fallbacks_total", "counter", (), (ROUTER,),
           ("catalogue", "disagg"),
           "Disagg-routed requests degraded to unified serving",
           router_labels=("reason",)),
    # -------------------------------------- engine: live roofline telemetry
    # (docs/OBSERVABILITY.md fleet pane): the engine reports its OWN
    # roofline position continuously from the rolling dispatch window —
    # the same arithmetic bench.py's JSON line uses (shared
    # production_stack_tpu/perf/roofline.py).
    Series("pstpu:live_tok_per_s", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "fleet-perf"),
           "Generation throughput over the rolling dispatch window"),
    Series("pstpu:live_hbm_bw_pct", "gauge", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "fleet-perf"),
           "Achieved fraction (percent) of the decode HBM roofline for "
           "the current batch shape"),
    Series("pstpu:live_effective_tokens_per_target_step", "gauge",
           ("model_name",), _BOTH_ENGINE, ("catalogue", "fleet-perf"),
           "Tokens emitted per target-model step over the rolling window "
           "(the Leviathan'23 amortization factor; >1 only when "
           "speculation pays)"),
    Series("pstpu:host_stall_seconds_total", "counter", ("model_name",),
           _BOTH_ENGINE, ("catalogue", "fleet-perf"),
           "Fetch-done to next issue-start gap with nothing outstanding "
           "on device (host scheduling stall, compile time excluded)"),
    Series("pstpu:dispatch_duration_seconds", "histogram",
           ("model_name", "train"), _BOTH_ENGINE,
           ("catalogue", "fleet-perf"),
           "Issue-to-fetch duration of each dispatch by train kind "
           "(prefill | decode | decode_spec)"),
    # ------------------------------------------------ router: fleet pane
    # One operator surface over what the scraper already holds per
    # backend (GET /fleet serves the JSON view of the same aggregate).
    Series("router_fleet_backends", "gauge", (), (ROUTER,),
           ("catalogue", "fleet-perf"),
           "Backends in the router's current fleet view (healthy "
           "serving endpoints)",
           router_labels=()),
    Series("router_fleet_live_tok_per_s", "gauge", (), (ROUTER,),
           ("catalogue", "fleet-perf"),
           "Engine-reported live generation throughput per backend",
           router_labels=("server",)),
    Series("router_fleet_live_hbm_bw_pct", "gauge", (), (ROUTER,),
           ("catalogue", "fleet-perf"),
           "Engine-reported live roofline position per backend "
           "(percent of the decode HBM ceiling)",
           router_labels=("server",)),
    Series("router_fleet_live_effective_tokens_per_target_step", "gauge",
           (), (ROUTER,), ("catalogue", "fleet-perf"),
           "Engine-reported tokens emitted per target-model step per "
           "backend (speculation amortization)",
           router_labels=("server",)),
    Series("router_fleet_breaker_open", "gauge", (), (ROUTER,),
           ("catalogue", "fleet-perf"),
           "Circuit-breaker position per backend (0 closed / 1 open / "
           "2 half-open) in the fleet view",
           router_labels=("server",)),
    Series("router_fleet_ramp_in_penalty", "gauge", (), (ROUTER,),
           ("catalogue", "fleet-perf"),
           "Remaining ramp-in load penalty per backend (1 just joined "
           "-> 0 fully ramped)",
           router_labels=("server",)),
)


def by_surface(surface: str) -> Dict[str, Series]:
    """name -> Series for one exporter surface."""
    return {s.name: s for s in REGISTRY if surface in s.surfaces}
