"""PL008 trace hazards: host sync, tracer branching, recompile churn.

Three hazard families inside *traced* code — function bodies reachable
from a ``jax.jit``/``pjit``/``lax.scan``/``while_loop``/``cond``/
``shard_map`` region per the module-local call graph
(tools/pstpu_lint/jaxmodel.py):

  * **host-sync calls** — ``.item()``, ``.block_until_ready()``,
    ``jax.device_get(...)``, and ``np.asarray``/``np.array``/``float()``/
    ``int()`` applied to a traced parameter. Inside a trace these either
    abort compilation (ConcretizationTypeError at the worst possible time
    — first request of a new shape family) or silently force a device
    sync per step;
  * **Python branching on tracer-typed parameters** — ``if``/``while``
    over a bare (non-static) parameter of the traced function. Static
    arguments declared via ``static_argnames`` are exempt, as is shape/
    dtype metadata (``x.shape[0] > 1`` is static and idiomatic);
  * **per-call-varying static arguments at dispatch sites** — passing
    ``time.*()``/``random.*()``/``datetime.*()`` into a jitted callable's
    ``static_argnames`` keyword recompiles on every call. The engine's
    convention is bucketing (``b=b, mb=mb`` through ``_bucket``), which
    this check leaves alone.

Like the rest of the suite the analysis is module-local: the engine's
traced impls, their helpers, and their dispatch sites all live in
engine/runner.py and ops/.
"""

import ast
from typing import List, Optional, Set

from tools.pstpu_lint import jaxmodel
from tools.pstpu_lint.callgraph import _own_statements
from tools.pstpu_lint.core import Finding

_HOST_SYNC_METHODS = {"item", "block_until_ready", "tolist"}
_NP_SYNC_FNS = {"asarray", "array"}
_VARYING_ROOTS = {"time", "random", "datetime", "uuid"}


def _param_names(node: ast.AST) -> Set[str]:
    args = node.args
    names = {a.arg for a in args.args + args.posonlyargs + args.kwonlyargs}
    names.discard("self")
    names.discard("cls")
    return names


def _references_param(expr: ast.AST, params: Set[str]) -> bool:
    """True when ``expr`` reads a traced parameter *directly* (a bare Name
    — not ``x.shape``/``x.dtype`` metadata, which is static)."""
    meta_reads = set()
    for node in ast.walk(expr):
        if (isinstance(node, ast.Attribute)
                and node.attr in ("shape", "dtype", "ndim", "size",
                                  "itemsize")
                and isinstance(node.value, ast.Name)):
            meta_reads.add(id(node.value))
    for node in ast.walk(expr):
        if (isinstance(node, ast.Name) and node.id in params
                and isinstance(node.ctx, ast.Load)
                and id(node) not in meta_reads):
            return True
    return False


def _bare_tracer_test(test: ast.AST, params: Set[str]) -> Optional[str]:
    """The offending parameter name when ``test`` is Python control flow
    over a bare tracer param: the param itself, a Compare/BoolOp/UnaryOp
    over bare params and constants. Attribute access (shape/dtype) makes
    the test static — not flagged."""
    if isinstance(test, ast.Name):
        return test.id if test.id in params else None
    if isinstance(test, ast.UnaryOp):
        return _bare_tracer_test(test.operand, params)
    if isinstance(test, ast.Compare):
        # Identity tests are static config dispatch, not tracer reads:
        # ``if ring_mesh is not None`` branches on whether an OPTIONAL
        # argument was provided, which is fixed at trace time.
        if any(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return None
        sides = [test.left] + list(test.comparators)
        hit = None
        for side in sides:
            if isinstance(side, ast.Name) and side.id in params:
                hit = side.id
            elif not isinstance(side, ast.Constant):
                return None   # derived expression — too static-likely
        return hit
    if isinstance(test, ast.BoolOp):
        for v in test.values:
            hit = _bare_tracer_test(v, params)
            if hit:
                return hit
    return None


def _is_varying_call(expr: ast.AST) -> bool:
    """time.time(), random.random(), datetime.now() shapes."""
    if not isinstance(expr, ast.Call):
        return False
    fn = expr.func
    root = None
    if isinstance(fn, ast.Attribute):
        node = fn
        while isinstance(node, ast.Attribute):
            node = node.value
        if isinstance(node, ast.Name):
            root = node.id
    elif isinstance(fn, ast.Name):
        root = fn.id
    return root in _VARYING_ROOTS


def check(relpath: str, tree: ast.AST, source: str) -> List[Finding]:
    model = jaxmodel.build(tree)
    findings: List[Finding] = []
    chains = model.traced_context()

    for qual, chain in chains.items():
        info = model.graph.functions.get(qual)
        if info is None:
            continue
        seed = chain[0]
        # The seed's static_argnames exempt the same NAMES down the call
        # chain too — the engine threads statics through by name
        # (``use_cached_window`` stays ``use_cached_window`` in helpers).
        static = set(model.seeds.get(seed, ()))
        params = _param_names(info.node)
        traced_params = params - static
        via = f" (traced via {' -> '.join(chain)})" if len(chain) > 1 \
            else f" (inside traced region {qual})"

        for node in _own_statements(info.node):
            # ---- host-sync calls --------------------------------------
            if isinstance(node, ast.Call):
                fn = node.func
                if (isinstance(fn, ast.Attribute)
                        and fn.attr in _HOST_SYNC_METHODS):
                    findings.append(Finding(
                        "PL008", relpath, node.lineno,
                        f".{fn.attr}() forces a host sync inside traced "
                        f"code{via}; keep the value on device or hoist the "
                        f"read out of the jit/scan region",
                    ))
                    continue
                if (isinstance(fn, ast.Attribute)
                        and fn.attr == "device_get"
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id == "jax"):
                    findings.append(Finding(
                        "PL008", relpath, node.lineno,
                        f"jax.device_get() inside traced code{via} breaks "
                        f"the trace; return the value instead",
                    ))
                    continue
                if (isinstance(fn, ast.Attribute)
                        and fn.attr in _NP_SYNC_FNS
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id in ("np", "numpy")
                        and node.args
                        and _references_param(node.args[0], traced_params)):
                    findings.append(Finding(
                        "PL008", relpath, node.lineno,
                        f"np.{fn.attr}() on a traced value{via} "
                        f"concretizes the tracer (host round-trip); use "
                        f"jnp inside the region",
                    ))
                    continue
                if (isinstance(fn, ast.Name) and fn.id in ("float", "int")
                        and node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in traced_params):
                    findings.append(Finding(
                        "PL008", relpath, node.lineno,
                        f"{fn.id}() on traced parameter "
                        f"{node.args[0].id!r}{via} concretizes the tracer; "
                        f"keep it a jnp scalar or mark the argument "
                        f"static",
                    ))
                    continue
            # ---- Python branching on tracer params --------------------
            if isinstance(node, (ast.If, ast.While)):
                hit = _bare_tracer_test(node.test, traced_params)
                if hit:
                    findings.append(Finding(
                        "PL008", relpath, node.lineno,
                        f"Python {type(node).__name__.lower()} on traced "
                        f"parameter {hit!r}{via} concretizes the tracer at "
                        f"trace time; use lax.cond/jnp.where, or declare "
                        f"it in static_argnames",
                    ))

    # ---- per-call-varying static args at dispatch sites ---------------
    varying_static = {
        key: b for key, b in model.bindings.items() if b.static_names
    }
    if varying_static:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            key = None
            if isinstance(node.func, ast.Name):
                key = node.func.id
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id in ("self", "cls")):
                key = f"self.{node.func.attr}"
            binding = varying_static.get(key) if key else None
            if binding is None and key and key.startswith("self."):
                binding = varying_static.get(key[len("self."):])
            if binding is None:
                continue
            for kw in node.keywords:
                if kw.arg in binding.static_names \
                        and _is_varying_call(kw.value):
                    findings.append(Finding(
                        "PL008", relpath, node.lineno,
                        f"static argument {kw.arg!r} of {binding.key} is "
                        f"per-call-varying here — every call compiles a "
                        f"fresh executable; bucket the value (engine "
                        f"_bucket idiom) or make it traced",
                    ))
    return findings
