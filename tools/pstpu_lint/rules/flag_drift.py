"""PL006 config-flag drift: every CLI flag is wired and documented.

A flag defined in the router parser or the engine entrypoint but never read
from the parsed namespace is dead config — operators set it, nothing
changes, nobody notices (the reference stack shipped exactly this bug in
its batch API). And a flag absent from README's flag tables is invisible
config. Each ``add_argument("--x")`` must have:

  * a reference: ``args.x`` / ``getattr(args, "x", ...)`` somewhere in the
    parser's own tier (scoped per parser — the router and engine parsers
    share dests like ``host``/``port``, so a package-wide search would let
    one tier's dead flag hide behind the other tier's read);
  * documentation: the literal ``--x`` appears in README.md (the generated
    flag tables — ``python -m tools.pstpu_lint.gen_docs`` — keep this
    satisfied automatically).

The helm leg extends the same contract one layer up, both directions:

  * every ``tpuConfig.*``/``routerSpec.*`` value a template renders next
    to a ``--flag`` must name a REAL flag of the matching parser
    (tpuConfig -> the engine entrypoint, routerSpec -> the router parser)
    — the next silently-dead helm knob fails here;
  * every such key must be declared in ``values.schema.json``;
  * reverse: every tpuConfig/routerSpec property in the schema (and every
    routerSpec key in ``values.yaml``) must be consumed by some template —
    a schema'd knob no template reads is dead config with documentation.
"""

import ast
import os
from typing import List, Set

from tools.pstpu_lint.core import Finding
from tools.pstpu_lint.flags import (
    scan_flags,
    scan_helm_schema_keys,
    scan_helm_values_keys,
    scan_helm_wirings,
)

# parser file -> package subtrees whose args.<dest> reads count for it.
PARSER_FILES = {
    "production_stack_tpu/router/parser.py":
        ("production_stack_tpu/router",),
    "production_stack_tpu/server/api_server.py":
        ("production_stack_tpu/server",),
}
README = "README.md"

HELM_TEMPLATES = "helm/templates"
HELM_VALUES = "helm/values.yaml"
HELM_SCHEMA = "helm/values.schema.json"
# helm section -> the parser whose flags it must name.
HELM_SECTION_PARSERS = {
    "tpuConfig": "production_stack_tpu/server/api_server.py",
    "routerSpec": "production_stack_tpu/router/parser.py",
}


def _referenced_dests(*scope_roots: str) -> Set[str]:
    """Every attr read off a name called ``args`` (or via getattr on it)
    under the given directories — the namespace objects argparse produces
    are consistently called ``args`` in this codebase."""
    paths: List[str] = []
    for scope in scope_roots:
        for root, dirs, files in os.walk(scope):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            paths += [os.path.join(root, n) for n in files
                      if n.endswith(".py")]
    dests: Set[str] = set()
    for path in paths:
        with open(path, encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read())
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "args"):
                dests.add(node.attr)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)
                  and node.func.id == "getattr"
                  and len(node.args) >= 2
                  and isinstance(node.args[0], ast.Name)
                  and node.args[0].id == "args"
                  and isinstance(node.args[1], ast.Constant)):
                dests.add(str(node.args[1].value))
    return dests


def check_flags(
    project_root: str,
    parser_files=None,
    readme=README,
) -> List[Finding]:
    parser_files = PARSER_FILES if parser_files is None else parser_files
    findings: List[Finding] = []
    readme_path = os.path.join(project_root, readme)
    with open(readme_path, encoding="utf-8") as f:
        readme_text = f.read()

    for rel, scopes in parser_files.items():
        referenced = _referenced_dests(
            *(os.path.join(project_root, s) for s in scopes)
        )
        with open(os.path.join(project_root, rel), encoding="utf-8") as f:
            source = f.read()
        for flag in scan_flags(source):
            if flag.dest not in referenced:
                findings.append(Finding(
                    "PL006", rel, flag.line,
                    f"flag {flag.option} is defined but args.{flag.dest} is "
                    f"never read in {', '.join(scopes)} — dead config "
                    f"(wire it or delete it)",
                ))
            if flag.option not in readme_text:
                findings.append(Finding(
                    "PL006", rel, flag.line,
                    f"flag {flag.option} is not documented in {readme} — "
                    f"regenerate the flag tables "
                    f"(python -m tools.pstpu_lint.gen_docs)",
                ))
    return findings


def check_helm(
    project_root: str,
    templates_dir=HELM_TEMPLATES,
    values_file=HELM_VALUES,
    schema_file=HELM_SCHEMA,
    section_parsers=None,
) -> List[Finding]:
    """The helm-drift leg (skips cleanly when the chart is absent)."""
    section_parsers = HELM_SECTION_PARSERS if section_parsers is None \
        else section_parsers
    tdir = os.path.join(project_root, templates_dir)
    schema_path = os.path.join(project_root, schema_file)
    values_path = os.path.join(project_root, values_file)
    if not (os.path.isdir(tdir) and os.path.exists(schema_path)):
        return []
    findings: List[Finding] = []

    parser_flags = {}
    for section, rel in section_parsers.items():
        path = os.path.join(project_root, rel)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                parser_flags[section] = {
                    fl.option for fl in scan_flags(f.read())}
    with open(schema_path, encoding="utf-8") as f:
        schema_keys = scan_helm_schema_keys(f.read())
    values_keys = {"routerSpec": set()}
    if os.path.exists(values_path):
        with open(values_path, encoding="utf-8") as f:
            values_keys = scan_helm_values_keys(f.read())

    referenced = {"tpuConfig": set(), "routerSpec": set()}
    for name in sorted(os.listdir(tdir)):
        if not name.endswith((".yaml", ".yml", ".tpl")):
            continue
        rel = f"{templates_dir}/{name}"
        with open(os.path.join(tdir, name), encoding="utf-8") as f:
            wirings = scan_helm_wirings(f.read())
        for w in wirings:
            if w.section not in referenced:
                continue
            referenced[w.section].add(w.key)
            flags = parser_flags.get(w.section)
            if w.flag is not None and flags is not None \
                    and w.flag not in flags:
                findings.append(Finding(
                    "PL006", rel, w.line,
                    f"helm key {w.dotted} renders flag {w.flag} which does "
                    f"not exist in {section_parsers[w.section]} — dead "
                    f"helm knob (operators set it, nothing changes)",
                ))
            if w.key not in schema_keys.get(w.section, set()):
                findings.append(Finding(
                    "PL006", rel, w.line,
                    f"helm key {w.dotted} is not declared in "
                    f"{schema_file} — schema validation silently passes "
                    f"typos of it",
                ))
    # Reverse direction: schema'd / defaulted keys no template consumes.
    for section, keys in schema_keys.items():
        for key in sorted(keys - referenced.get(section, set())):
            findings.append(Finding(
                "PL006", schema_file, 1,
                f"helm key {section}.{key} is declared in the schema but "
                f"no template under {templates_dir} consumes it — dead "
                f"config with documentation",
            ))
    for section, keys in values_keys.items():
        for key in sorted(keys - schema_keys.get(section, set())):
            findings.append(Finding(
                "PL006", values_file, 1,
                f"helm key {section}.{key} has a default in {values_file} "
                f"but is missing from {schema_file}",
            ))
    return findings


# ------------------------------------------------------------- registration
def wants(project_root: str) -> bool:
    return all(
        os.path.exists(os.path.join(project_root, p))
        for p in tuple(PARSER_FILES) + (README,)
    )


def check(project_root: str) -> List[Finding]:
    findings = check_flags(project_root)
    # Freshness of the GENERATED README flag tables is part of this rule
    # (PL006's documentation leg would otherwise stay green on a stale
    # table whose '--flag' literals still match).
    from tools.pstpu_lint import gen_docs

    for tier, relpath, what in gen_docs.check_flag_tables(project_root):
        findings.append(Finding(
            "PL006", relpath, 1,
            f"README flag table {tier!r} is {what}; run "
            f"python -m tools.pstpu_lint.gen_docs",
        ))
    findings += check_helm(project_root)
    return findings
