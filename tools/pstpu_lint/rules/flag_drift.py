"""PL006 config-flag drift: every CLI flag is wired and documented.

A flag defined in the router parser or the engine entrypoint but never read
from the parsed namespace is dead config — operators set it, nothing
changes, nobody notices (the reference stack shipped exactly this bug in
its batch API). And a flag absent from README's flag tables is invisible
config. Each ``add_argument("--x")`` must have:

  * a reference: ``args.x`` / ``getattr(args, "x", ...)`` somewhere in the
    parser's own tier (scoped per parser — the router and engine parsers
    share dests like ``host``/``port``, so a package-wide search would let
    one tier's dead flag hide behind the other tier's read);
  * documentation: the literal ``--x`` appears in README.md (the generated
    flag tables — ``python -m tools.pstpu_lint.gen_docs`` — keep this
    satisfied automatically).
"""

import ast
import os
from typing import List, Set

from tools.pstpu_lint.core import Finding
from tools.pstpu_lint.flags import scan_flags

# parser file -> package subtrees whose args.<dest> reads count for it.
PARSER_FILES = {
    "production_stack_tpu/router/parser.py":
        ("production_stack_tpu/router",),
    "production_stack_tpu/server/api_server.py":
        ("production_stack_tpu/server",),
}
README = "README.md"


def _referenced_dests(*scope_roots: str) -> Set[str]:
    """Every attr read off a name called ``args`` (or via getattr on it)
    under the given directories — the namespace objects argparse produces
    are consistently called ``args`` in this codebase."""
    paths: List[str] = []
    for scope in scope_roots:
        for root, dirs, files in os.walk(scope):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            paths += [os.path.join(root, n) for n in files
                      if n.endswith(".py")]
    dests: Set[str] = set()
    for path in paths:
        with open(path, encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read())
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "args"):
                dests.add(node.attr)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)
                  and node.func.id == "getattr"
                  and len(node.args) >= 2
                  and isinstance(node.args[0], ast.Name)
                  and node.args[0].id == "args"
                  and isinstance(node.args[1], ast.Constant)):
                dests.add(str(node.args[1].value))
    return dests


def check_flags(
    project_root: str,
    parser_files=None,
    readme=README,
) -> List[Finding]:
    parser_files = PARSER_FILES if parser_files is None else parser_files
    findings: List[Finding] = []
    readme_path = os.path.join(project_root, readme)
    with open(readme_path, encoding="utf-8") as f:
        readme_text = f.read()

    for rel, scopes in parser_files.items():
        referenced = _referenced_dests(
            *(os.path.join(project_root, s) for s in scopes)
        )
        with open(os.path.join(project_root, rel), encoding="utf-8") as f:
            source = f.read()
        for flag in scan_flags(source):
            if flag.dest not in referenced:
                findings.append(Finding(
                    "PL006", rel, flag.line,
                    f"flag {flag.option} is defined but args.{flag.dest} is "
                    f"never read in {', '.join(scopes)} — dead config "
                    f"(wire it or delete it)",
                ))
            if flag.option not in readme_text:
                findings.append(Finding(
                    "PL006", rel, flag.line,
                    f"flag {flag.option} is not documented in {readme} — "
                    f"regenerate the flag tables "
                    f"(python -m tools.pstpu_lint.gen_docs)",
                ))
    return findings


# ------------------------------------------------------------- registration
def wants(project_root: str) -> bool:
    return all(
        os.path.exists(os.path.join(project_root, p))
        for p in tuple(PARSER_FILES) + (README,)
    )


def check(project_root: str) -> List[Finding]:
    findings = check_flags(project_root)
    # Freshness of the GENERATED README flag tables is part of this rule
    # (PL006's documentation leg would otherwise stay green on a stale
    # table whose '--flag' literals still match).
    from tools.pstpu_lint import gen_docs

    for tier, relpath, what in gen_docs.check_flag_tables(project_root):
        findings.append(Finding(
            "PL006", relpath, 1,
            f"README flag table {tier!r} is {what}; run "
            f"python -m tools.pstpu_lint.gen_docs",
        ))
    return findings
