"""PL002 fire-and-forget tasks: dropped ``asyncio.create_task`` handles.

A task whose only reference is the event loop's weak set can be garbage
collected mid-flight, and its exception is silently swallowed at GC time —
the engine's KV-handoff publisher and the router's streaming pumps both
learned this the hard way. Every created task must either be stored
(``self._task = create_task(...)``, appended to a collection) or given an
``add_done_callback``; a bare expression statement (or assignment to
``_``) drops it.

Receiver-aware: only ``asyncio.create_task``/``ensure_future``, bare
imported names, and ``<something loop-ish>.create_task`` count — a domain
method that happens to be called ``create_task`` (``self.scheduler.
create_task(...)``) is not an asyncio spawn, and ``tg.create_task(...)``
inside ``asyncio.TaskGroup`` holds a strong reference and propagates
exceptions by design, so neither is flagged.
"""

import ast
from typing import List

from tools.pstpu_lint.core import Finding

_SPAWN_FNS = {"create_task", "ensure_future"}


def _loopish(name: str) -> bool:
    return "loop" in name.lower()


def _is_spawn(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        # `from asyncio import create_task` style; a same-named local
        # function is a rare acceptable false positive (waivable).
        return fn.id in _SPAWN_FNS
    if isinstance(fn, ast.Attribute) and fn.attr in _SPAWN_FNS:
        recv = fn.value
        if isinstance(recv, ast.Name):
            return recv.id == "asyncio" or _loopish(recv.id)
        if isinstance(recv, ast.Attribute):
            return _loopish(recv.attr)
        if isinstance(recv, ast.Call):
            f = recv.func
            inner = (f.attr if isinstance(f, ast.Attribute)
                     else f.id if isinstance(f, ast.Name) else "")
            return _loopish(inner)   # asyncio.get_event_loop().create_task
    return False


def check(relpath: str, tree: ast.AST, source: str) -> List[Finding]:
    findings = []
    for node in ast.walk(tree):
        call = None
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
        elif (isinstance(node, ast.Assign)
              and isinstance(node.value, ast.Call)
              and all(isinstance(t, ast.Name) and t.id == "_"
                      for t in node.targets)):
            call = node.value
        if call is None or not _is_spawn(call):
            continue
        findings.append(Finding(
            "PL002", relpath, call.lineno,
            "asyncio task handle is dropped — store it (or chain "
            ".add_done_callback) so it cannot be GC'd mid-flight and its "
            "exception is observed",
        ))
    return findings
