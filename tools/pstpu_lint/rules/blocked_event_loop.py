"""PL001 blocked-event-loop: sync I/O reachable inside ``async def`` bodies.

The router is one process, one event loop; a single ``time.sleep`` or
blocking ``requests.get`` in a handler stalls EVERY in-flight stream.
Flagged calls: ``time.sleep``, builtin ``open``, ``socket.socket`` /
``socket.create_connection``, ``subprocess.*``, ``requests.*``, and
``urllib.request.urlopen`` — when the enclosing function body runs on the
event loop per the module-local call graph (async defs + sync helpers they
call). Thread targets and executor targets are exempt by construction: they
are passed as values, never called from async context, so the call graph
never seeds them (tools/pstpu_lint/callgraph.py).
"""

import ast
from typing import List

from tools.pstpu_lint.callgraph import CallGraph, _own_statements
from tools.pstpu_lint.core import Finding

_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output", "Popen",
                   "getoutput", "getstatusoutput"}
_SOCKET_FNS = {"socket", "create_connection"}


def _flagged_call(node: ast.Call) -> str:
    """Return a human-readable name when this call blocks, else ''."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "open":
        return "open()"
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        root, attr = fn.value.id, fn.attr
        if root == "time" and attr == "sleep":
            return "time.sleep()"
        if root == "requests":
            return f"requests.{attr}()"
        if root == "subprocess" and attr in _SUBPROCESS_FNS:
            return f"subprocess.{attr}()"
        if root == "socket" and attr in _SOCKET_FNS:
            return f"socket.{attr}()"
    if (isinstance(fn, ast.Attribute) and fn.attr == "urlopen"
            and isinstance(fn.value, ast.Attribute)
            and fn.value.attr == "request"
            and isinstance(fn.value.value, ast.Name)
            and fn.value.value.id == "urllib"):
        return "urllib.request.urlopen()"
    return ""


def check(relpath: str, tree: ast.AST, source: str) -> List[Finding]:
    graph = CallGraph(tree)
    chains = graph.async_context()
    findings = []
    for qual, chain in chains.items():
        info = graph.functions[qual]
        for node in _own_statements(info.node):
            if not isinstance(node, ast.Call):
                continue
            name = _flagged_call(node)
            if not name:
                continue
            via = ""
            if len(chain) > 1:
                via = f" (reachable from async def {chain[0]} via " \
                      f"{' -> '.join(chain[1:])})"
            elif not info.is_async:
                continue   # unreachable, defensive
            else:
                via = f" (inside async def {qual})"
            findings.append(Finding(
                "PL001", relpath, node.lineno,
                f"{name} blocks the event loop{via}; use the async "
                f"equivalent or run_in_executor",
            ))
    return findings
