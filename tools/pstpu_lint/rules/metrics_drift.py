"""PL004 metrics-drift: renderers, registry, and docs must agree.

The stack has two parallel engine /metrics renderers (the hand-rolled text
renderer in server/metrics.py the API server serves, and the
prometheus_client Collector in engine/metrics.py) plus the router's own
registry. A series added to one renderer but not the other, a label set
that differs between them, a name outside the ``pstpu:``/``router_``/
``vllm:`` convention, a duplicate declaration, or a series missing from the
docs tables is exactly the silent drift the dashboards then chart wrong —
or chart nothing.

Checks, all against tools/pstpu_lint/metrics_registry.py:
  1. every statically-extracted series name uses an allowed prefix;
  2. no series is declared twice on one surface;
  3. each surface's extracted name set == the registry's set for it;
  4. extracted label sets match the registry (and the two engine surfaces
     carry identical label sets for shared series);
  5. the generated docs tables (gen_docs markers) are up to date.
"""

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from tools.pstpu_lint import metrics_registry as reg
from tools.pstpu_lint.core import Finding

SERVER_METRICS = "production_stack_tpu/server/metrics.py"
ENGINE_METRICS = "production_stack_tpu/engine/metrics.py"
ROUTER_METRICS = "production_stack_tpu/router/metrics.py"

# name -> (kind, labels-or-None, line, relpath-or-None); labels None = not
# statically visible; relpath None = the surface's default renderer file
# (histogram names live in engine/metrics.py but render on the text surface,
# so their findings must point there).
Extracted = Dict[
    str, Tuple[str, Optional[Tuple[str, ...]], int, Optional[str]]
]


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _const_str_list(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.List, ast.Tuple)):
        vals = [_const_str(e) for e in node.elts]
        if all(v is not None for v in vals):
            return tuple(vals)
    return None


def _labels_from_source(name: str, source: str) -> Optional[Tuple[str, ...]]:
    """Label keys of a text-renderer emission, from the f-string source.

    ``name{label}`` uses the shared per-model label placeholder;
    ``name{{k="...",`` spells labels inline (possibly across a line break).
    Returns None when the emission is not statically visible (e.g. rendered
    through the histogram helper).
    """
    idx = source.find(name + "{")
    if idx < 0:
        return None
    window = source[idx + len(name):idx + len(name) + 220]
    if window.startswith("{label}"):
        return ("model_name",)
    if window.startswith("{{"):
        # Collect k=" keys up to the closing }} (f-string literals may be
        # split across adjacent string parts; the window spans them).
        end = window.find("}}")
        body = window[2:end if end > 0 else len(window)]
        keys = re.findall(r'(\w+)="', body)
        return tuple(dict.fromkeys(keys)) or None
    return None


def extract_engine_text(server_src: str,
                        engine_src: Optional[str] = None) -> Extracted:
    """Series of the text renderer: '# TYPE <name> <kind>' constants, plus
    the histogram names it renders via RequestLatencyHistograms."""
    out: Extracted = {}
    dupes: List[Tuple[str, int]] = []
    tree = ast.parse(server_src)
    for node in ast.walk(tree):
        val = _const_str(node)
        if val is None or not val.startswith("# TYPE "):
            continue
        parts = val.split()
        if len(parts) != 4:
            continue
        _h, _t, name, kind = parts
        line = node.lineno
        if name in out:
            dupes.append((name, line))
            continue
        out[name] = (kind, _labels_from_source(name, server_src), line, None)
    if engine_src:
        etree = ast.parse(engine_src)
        for node in ast.walk(etree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "render" and node.args):
                name = _const_str(node.args[0])
                if name and name.startswith(reg.ALLOWED_PREFIXES):
                    out.setdefault(
                        name,
                        ("histogram", None, node.lineno, ENGINE_METRICS),
                    )
    out["__duplicates__"] = dupes  # type: ignore[assignment]
    return out


def extract_engine_collector(engine_src: str) -> Extracted:
    """Series of the prometheus_client Collector: gauge()/counter() helper
    calls plus explicit *MetricFamily constructions with constant names."""
    out: Extracted = {}
    dupes: List[Tuple[str, int]] = []
    tree = ast.parse(engine_src)
    default_labels: Optional[Tuple[str, ...]] = None
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "labels"):
            lst = _const_str_list(node.value)
            if lst is not None:
                default_labels = lst

    def _add(name, kind, labels, line):
        if name in out:
            dupes.append((name, line))
        else:
            out[name] = (kind, labels, line, None)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in ("gauge", "counter",
                                                  "histogram"):
            name = _const_str(node.args[0]) if node.args else None
            if name:
                kind = fn.id
                _add(name, kind, default_labels, node.lineno)
        elif isinstance(fn, ast.Name) and fn.id in (
            "GaugeMetricFamily", "CounterMetricFamily",
            "HistogramMetricFamily",
        ):
            name = _const_str(node.args[0]) if node.args else None
            if not name:
                continue
            kind = ("gauge" if fn.id.startswith("Gauge")
                    else "counter" if fn.id.startswith("Counter")
                    else "histogram")
            if kind == "counter" and not name.endswith("_total"):
                name += "_total"   # prometheus_client appends _total
            labels = None
            for kw in node.keywords:
                if kw.arg == "labels":
                    labels = _const_str_list(kw.value)
            _add(name, kind, labels, node.lineno)
    out["__duplicates__"] = dupes  # type: ignore[assignment]
    return out


def extract_router(router_src: str) -> Extracted:
    """Series of the router's prometheus_client module registry."""
    out: Extracted = {}
    dupes: List[Tuple[str, int]] = []
    tree = ast.parse(router_src)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        ctor = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if ctor not in ("Gauge", "Counter", "Histogram"):
            continue
        name = _const_str(node.args[0]) if node.args else None
        if name is None:
            continue
        kind = ctor.lower()
        if kind == "counter" and not name.endswith("_total"):
            name += "_total"
        labels: Optional[Tuple[str, ...]] = ()
        if len(node.args) >= 3:
            labels = _const_str_list(node.args[2])
        for kw in node.keywords:
            if kw.arg in ("labelnames", "labels"):
                labels = _const_str_list(kw.value)
        if name in out:
            dupes.append((name, node.lineno))
        else:
            out[name] = (kind, labels, node.lineno, None)
    out["__duplicates__"] = dupes  # type: ignore[assignment]
    return out


# ---------------------------------------------------------------- the check
def _check_surface(
    surface: str, extracted: Extracted, relpath: str,
    registry: Tuple[reg.Series, ...],
) -> List[Finding]:
    findings = []
    dupes = extracted.pop("__duplicates__", [])  # type: ignore[arg-type]
    for name, line in dupes:
        findings.append(Finding(
            "PL004", relpath, line,
            f"series {name!r} is declared more than once in this renderer",
        ))
    expected = {s.name: s for s in registry if surface in s.surfaces}
    for name, (kind, labels, line, src_file) in extracted.items():
        where = src_file or relpath
        if not name.startswith(reg.ALLOWED_PREFIXES):
            findings.append(Finding(
                "PL004", where, line,
                f"series {name!r} violates the naming convention (allowed "
                f"prefixes: {', '.join(reg.ALLOWED_PREFIXES)})",
            ))
        entry = expected.get(name)
        if entry is None:
            findings.append(Finding(
                "PL004", where, line,
                f"series {name!r} is not in the metrics registry — add it "
                f"to tools/pstpu_lint/metrics_registry.py and regenerate "
                f"the docs tables (python -m tools.pstpu_lint.gen_docs)",
            ))
            continue
        if entry.kind != kind:
            findings.append(Finding(
                "PL004", where, line,
                f"series {name!r} is a {kind} here but a {entry.kind} in "
                f"the registry",
            ))
        want = entry.labels_for(surface)
        if labels is not None and tuple(labels) != tuple(want):
            findings.append(Finding(
                "PL004", where, line,
                f"series {name!r} label set {tuple(labels)!r} does not "
                f"match the registry ({tuple(want)!r}); the parallel "
                f"renderers must agree",
            ))
    for name, entry in expected.items():
        if name not in extracted:
            findings.append(Finding(
                "PL004", relpath, 1,
                f"series {name!r} is in the registry for surface "
                f"{surface!r} but this renderer does not emit it",
            ))
    return findings


def check_metrics(
    project_root: str,
    registry: Optional[Tuple[reg.Series, ...]] = None,
    docs_check: bool = True,
) -> List[Finding]:
    registry = reg.REGISTRY if registry is None else registry
    findings: List[Finding] = []

    def _read(rel):
        with open(os.path.join(project_root, rel), encoding="utf-8") as f:
            return f.read()

    server_src = _read(SERVER_METRICS)
    engine_src = _read(ENGINE_METRICS)
    router_src = _read(ROUTER_METRICS)

    findings += _check_surface(
        reg.ENGINE_TEXT, extract_engine_text(server_src, engine_src),
        SERVER_METRICS, registry,
    )
    findings += _check_surface(
        reg.ENGINE_COLLECTOR, extract_engine_collector(engine_src),
        ENGINE_METRICS, registry,
    )
    findings += _check_surface(
        reg.ROUTER, extract_router(router_src), ROUTER_METRICS, registry,
    )

    # Label agreement between the two engine renderers is structural: one
    # registry entry carries one label set for both surfaces, and each
    # surface was checked against it above.

    if docs_check:
        from tools.pstpu_lint import gen_docs

        for group, relpath, stale in gen_docs.check_tables(
            project_root, registry=registry
        ):
            findings.append(Finding(
                "PL004", relpath, 1,
                f"docs metrics table {group!r} is {stale}; run "
                f"python -m tools.pstpu_lint.gen_docs",
            ))
    return findings


# ------------------------------------------------------------- registration
def wants(project_root: str) -> bool:
    return all(
        os.path.exists(os.path.join(project_root, p))
        for p in (SERVER_METRICS, ENGINE_METRICS, ROUTER_METRICS)
    )


def check(project_root: str) -> List[Finding]:
    return check_metrics(project_root)
