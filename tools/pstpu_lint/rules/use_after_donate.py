"""PL007 use-after-donate: reading a buffer after a dispatch donated it.

``jax.jit(..., donate_argnums=...)`` hands the argument buffers to XLA:
after the dispatch the Python bindings still *name* them, but the device
memory is gone (reads raise ``RuntimeError`` on TPU, ``ValueError``
INVALID_ARGUMENT on CPU — and only when the timing loses, which is why this
bug class ships). The engine's contract is the runner.py rebind idiom:
every dispatch that donates the KV pools returns the new buffers and the
call site rebinds them **in the same statement** —

    self.kv_k, self.kv_v = ... = self._decode(..., self.kv_k, self.kv_v, ...)

This rule makes that idiom the checked contract. Per module it builds the
jit binding graph (tools/pstpu_lint/jaxmodel.py): which bindings hold a
donating dispatch (direct ``jax.jit`` assignments, decorated defs, and
one-level factories), with which ``donate_argnums``. Then, per function
body, statements are scanned in source order:

  * a call through a donating binding marks the argument bindings at the
    donated positions (locals and ``self.*`` attrs) as *consumed* — unless
    the same statement's assignment targets rebind them;
  * any later read of a consumed binding is flagged, until a rebinding
    assignment clears it;
  * reads inside a ``try`` whose handler catches ``RuntimeError`` or
    ``ValueError`` are exempt — that is the linted donation-retry guard
    (``runner.read_blocks_retry``); a bare ``except Exception`` guard is
    NOT accepted (type it, or waive with a reason).

The analysis is intra-function and flow-insensitive across branches
(statements in source order), which is exactly the shape of the real
dispatch sites; cross-function donation would mean a dispatch's caller
holds stale pool refs across frames — worth a human's eyes, not a
heuristic's.
"""

import ast
from typing import List, Optional, Set

from tools.pstpu_lint import jaxmodel
from tools.pstpu_lint.core import Finding

_RETRYISH = {"RuntimeError", "ValueError"}


def _walk_pruned(node: ast.AST):
    """ast.walk that does not descend into nested function/class bodies
    (they are separate execution contexts with their own scan)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def _read_key(node: ast.AST) -> Optional[str]:
    """Binding key of a Name/self-attr expression ('wk' / 'self.kv_k')."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")):
        return f"self.{node.attr}"
    return None


def _flatten_targets(target: ast.AST, out: Set[str]) -> None:
    if isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            _flatten_targets(e, out)
    elif isinstance(target, ast.Starred):
        _flatten_targets(target.value, out)
    else:
        key = _read_key(target)
        if key is not None:
            out.add(key)


def _stmt_targets(stmt: ast.stmt) -> Set[str]:
    out: Set[str] = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            _flatten_targets(t, out)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        if stmt.target is not None:
            _flatten_targets(stmt.target, out)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        _flatten_targets(stmt.target, out)
    return out


def _catches_retryish(try_node: ast.Try) -> bool:
    for handler in try_node.handlers:
        t = handler.type
        names = []
        if isinstance(t, ast.Tuple):
            names = [e.id for e in t.elts if isinstance(e, ast.Name)]
        elif isinstance(t, ast.Name):
            names = [t.id]
        if any(n in _RETRYISH for n in names):
            return True
    return False


class _BodyScan:
    """Source-order scan of one function body, tracking consumed bindings."""

    def __init__(self, relpath: str, model: jaxmodel.JaxModel):
        self.relpath = relpath
        self.model = model
        self.consumed: dict = {}          # key -> (dispatch line, binding key)
        self.findings: List[Finding] = []

    # ------------------------------------------------------------- helpers
    def _donating_calls(self, stmt: ast.stmt):
        """(call, binding) pairs for donating-jit calls inside ``stmt``."""
        for node in _walk_pruned(stmt):
            if not isinstance(node, ast.Call):
                continue
            key = _read_key(node.func)
            if key is None:
                continue
            binding = self.model.bindings.get(key)
            if binding is None and key.startswith("self."):
                binding = self.model.bindings.get(key[len("self."):])
            if binding is not None and binding.donate:
                yield node, binding

    def _check_reads(self, stmt: ast.stmt, exempt: bool) -> None:
        if exempt or not self.consumed:
            return
        for node in _walk_pruned(stmt):
            key = None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                key = node.id
            elif (isinstance(node, ast.Attribute)
                  and isinstance(node.ctx, ast.Load)
                  and isinstance(node.value, ast.Name)
                  and node.value.id in ("self", "cls")):
                key = f"self.{node.attr}"
            if key is None or key not in self.consumed:
                continue
            disp_line, disp_key = self.consumed[key]
            self.findings.append(Finding(
                "PL007", self.relpath, node.lineno,
                f"{key} was donated to the dispatch through {disp_key} "
                f"(line {disp_line}) and never rebound from its outputs — "
                f"the buffer is deleted; rebind it from the dispatch's "
                f"returns or guard the read with the donation-retry idiom "
                f"(except (RuntimeError, ValueError))",
            ))

    def _apply_stmt_effects(self, stmt: ast.stmt) -> None:
        donated_now: Set[str] = set()
        for call, binding in self._donating_calls(stmt):
            for pos in binding.donate:
                if pos < len(call.args):
                    key = _read_key(call.args[pos])
                    if key is not None:
                        donated_now.add(key)
            if donated_now:
                for key in donated_now:
                    self.consumed.setdefault(key, (call.lineno, binding.key))
        # Assignment targets of the SAME statement rebind (the idiom);
        # later assignments clear earlier donations.
        for key in _stmt_targets(stmt):
            self.consumed.pop(key, None)

    # ---------------------------------------------------------------- walk
    @staticmethod
    def _headers(stmt: ast.stmt) -> List[ast.AST]:
        """The expressions a compound statement evaluates BEFORE its body
        (its bodies are scanned recursively with their own exemption)."""
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.iter]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return [item.context_expr for item in stmt.items]
        return []

    def scan(self, body: List[ast.stmt], exempt: bool = False) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.Try, ast.If, ast.For, ast.AsyncFor,
                                 ast.While, ast.With, ast.AsyncWith)):
                for header in self._headers(stmt):
                    self._check_reads(header, exempt)
                    self._apply_stmt_effects(header)
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    for key in _stmt_targets(stmt):
                        self.consumed.pop(key, None)
                if isinstance(stmt, ast.Try):
                    sub_exempt = exempt or _catches_retryish(stmt)
                    self.scan(stmt.body, sub_exempt)
                    for handler in stmt.handlers:
                        self.scan(handler.body, exempt)
                    self.scan(stmt.orelse, exempt)
                    self.scan(stmt.finalbody, exempt)
                elif isinstance(stmt, (ast.If, ast.For, ast.AsyncFor,
                                       ast.While)):
                    self.scan(stmt.body, exempt)
                    self.scan(stmt.orelse, exempt)
                else:
                    self.scan(stmt.body, exempt)
                continue
            self._check_reads(stmt, exempt)
            self._apply_stmt_effects(stmt)


def check(relpath: str, tree: ast.AST, source: str) -> List[Finding]:
    model = jaxmodel.build(tree)
    if not any(b.donate for b in model.bindings.values()):
        return []
    findings: List[Finding] = []
    for qual, info in model.graph.functions.items():
        body = getattr(info.node, "body", None)
        if not body:
            continue
        scan = _BodyScan(relpath, model)
        scan.scan(body)
        findings.extend(scan.findings)
    return findings
