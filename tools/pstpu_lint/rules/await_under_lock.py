"""PL005 await-under-lock: ``await`` while holding a ``threading.Lock``.

A sync ``with lock:`` held across an ``await`` is a deadlock factory: the
coroutine parks with the lock held, the scheduler thread (or any executor
worker) that needs the same lock blocks forever, and the event loop happily
keeps running everything EXCEPT the thing that would release it. The engine
loop/runner/scheduler share state with the stats scrapers through
threading locks, so this shape is reachable. ``async with
asyncio.Lock()`` is the correct construct and is not flagged.
"""

import ast
from typing import List

from tools.pstpu_lint.core import Finding

_LOCKISH = ("lock", "mutex")


def _lock_name(expr: ast.AST) -> str:
    """Terminal identifier of the context manager expression, if lock-ish."""
    name = ""
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Call):
        return _lock_name(expr.func)
    low = name.lower()
    return name if any(tok in low for tok in _LOCKISH) else ""


def _awaits_in_body(with_node: ast.With):
    stack = list(with_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue   # nested defs are separate execution contexts
        if isinstance(node, ast.Await):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def check(relpath: str, tree: ast.AST, source: str) -> List[Finding]:
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):   # async with is fine
            continue
        held = [
            _lock_name(item.context_expr) for item in node.items
            if _lock_name(item.context_expr)
        ]
        if not held:
            continue
        for aw in _awaits_in_body(node):
            # Anchored at the WITH line (where the fix — and a waiver —
            # naturally goes), naming the await's own line in the message.
            findings.append(Finding(
                "PL005", relpath, node.lineno,
                f"await (line {aw.lineno}) while holding threading lock "
                f"{held[0]!r} — the coroutine can park with the lock held "
                f"and deadlock every other thread; use asyncio.Lock or "
                f"release before awaiting",
            ))
    return findings
