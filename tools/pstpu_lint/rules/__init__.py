"""Rule registry.

FILE_RULES: (code, scope-prefixes or None, fn(relpath, tree, source)).
PROJECT_RULES: (code, wants(project_root), fn(project_root)).

Scopes are project-relative path prefixes; ``None`` means every linted
file. The data-plane scope is where the event-loop/exception rules bite —
the engine tier runs its blocking work on executors by design and is
covered by the narrower rules only.
"""

from tools.pstpu_lint.rules import (
    await_under_lock,
    blocked_event_loop,
    fire_and_forget,
    flag_drift,
    metrics_drift,
    swallowed_exceptions,
)

DATA_PLANE_SCOPES = (
    "production_stack_tpu/router",
    "production_stack_tpu/server",
    "production_stack_tpu/disagg",
    "production_stack_tpu/kv_offload",
)

FILE_RULES = [
    ("PL001", DATA_PLANE_SCOPES, blocked_event_loop.check),
    ("PL002", None, fire_and_forget.check),
    ("PL003", DATA_PLANE_SCOPES + ("production_stack_tpu/tracing.py",),
     swallowed_exceptions.check),
    ("PL005", None, await_under_lock.check),
]

PROJECT_RULES = [
    ("PL004", metrics_drift.wants, metrics_drift.check),
    ("PL006", flag_drift.wants, flag_drift.check),
]
