"""Rule registry.

FILE_RULES: (code, scope-prefixes or None, fn(relpath, tree, source)).
PROJECT_RULES: (code, wants(project_root), fn(project_root)).

Scopes are project-relative path prefixes; ``None`` means every linted
file. The data-plane scope is where the event-loop/exception rules bite —
the engine tier runs its blocking work on executors by design and is
covered by the narrower rules only.
"""

from tools.pstpu_lint.rules import (
    await_under_lock,
    blocked_event_loop,
    fire_and_forget,
    flag_drift,
    http_drift,
    metrics_drift,
    shared_state_race,
    swallowed_exceptions,
    trace_hazards,
    use_after_donate,
    wire_drift,
)

DATA_PLANE_SCOPES = (
    "production_stack_tpu/router",
    "production_stack_tpu/server",
    "production_stack_tpu/disagg",
    "production_stack_tpu/kv_offload",
)

# The JAX plane: where jit dispatch, donation, and tracing happen. The
# donation/trace rules are cheap no-ops on modules with no jit bindings,
# but scoping keeps their heuristics away from test fixtures and scripts.
JAX_PLANE_SCOPES = (
    "production_stack_tpu/engine",
    "production_stack_tpu/ops",
    "production_stack_tpu/models",
    "production_stack_tpu/parallel",
)

FILE_RULES = [
    ("PL001", DATA_PLANE_SCOPES, blocked_event_loop.check),
    ("PL002", None, fire_and_forget.check),
    # engine/runner.py rides along for PL003: its donation-race guards
    # must be typed (RuntimeError/ValueError) or carry a reasoned waiver.
    ("PL003", DATA_PLANE_SCOPES + ("production_stack_tpu/tracing.py",
                                   "production_stack_tpu/engine/runner.py"),
     swallowed_exceptions.check),
    ("PL005", None, await_under_lock.check),
    ("PL007", JAX_PLANE_SCOPES, use_after_donate.check),
    ("PL008", JAX_PLANE_SCOPES, trace_hazards.check),
    ("PL009", DATA_PLANE_SCOPES + JAX_PLANE_SCOPES,
     shared_state_race.check),
]

PROJECT_RULES = [
    ("PL004", metrics_drift.wants, metrics_drift.check),
    ("PL006", flag_drift.wants, flag_drift.check),
    ("PL010", wire_drift.wants, wire_drift.check),
    # The HTTP control surface (tools/pstpu_lint/http_registry.py): one
    # registry, three families — headers, routes, status semantics.
    ("PL011", http_drift.wants, http_drift.check_headers),
    ("PL012", http_drift.wants, http_drift.check_routes),
    ("PL013", http_drift.wants, http_drift.check_status),
]
