"""PL003 swallowed exceptions: silent catch-alls in the data plane.

``except Exception: pass`` in the router/server/disagg/kv_offload tiers
turns backend failures into invisible ones — the resilience layer can only
open circuits and the operator can only alert on what is logged or counted.
A catch-all handler (bare ``except:``, ``except Exception``, ``except
BaseException``) must do at least one of:

  * re-raise (``raise``),
  * log (any ``logger.*`` / ``logging.*`` call),
  * bump a metric (``.inc()`` / ``.observe()``, a metric-receiver
    ``.set()``, or an ``x += ...`` on a ``*_total`` counter attribute),
  * actually use the caught exception (``except Exception as e`` with ``e``
    read in the body — returning a 400 carrying ``{e}`` or relaying it over
    a queue surfaces the failure; it is not swallowed).

Returning a bare fallback value alone is not evidence — that is exactly
the silent-degradation shape this rule exists to catch.
"""

import ast
from typing import List

from tools.pstpu_lint.core import Finding

_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}
# .inc()/.observe() only exist on metric objects; .set() also exists on
# threading/asyncio Event — a shutdown signal is NOT failure evidence, so
# .set() counts only when its receiver looks like a metric (a .labels(...)
# chain or a metric/gauge/counter-ish name).
_METRIC_METHODS = {"inc", "observe"}
_METRICISH = ("metric", "gauge", "counter", "histogram")


def _metricish_receiver(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "labels":
            return True
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and any(tok in name.lower() for tok in _METRICISH):
            return True
    return False


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in ("Exception", "BaseException") for n in names)


def _has_evidence(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if (handler.name and isinstance(node, ast.Name)
                and node.id == handler.name
                and isinstance(node.ctx, ast.Load)):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _METRIC_METHODS:
                return True
            if attr == "set" and _metricish_receiver(node.func.value):
                return True
            if attr in _LOG_METHODS:
                root = node.func.value
                # logger.warning(...), logging.warning(...),
                # self.logger.info(...), metrics-ish chains all count.
                if isinstance(root, (ast.Name, ast.Attribute)):
                    return True
        if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
            target = node.target
            if (isinstance(target, ast.Attribute)
                    and target.attr.endswith("_total")):
                return True
    return False


def check(relpath: str, tree: ast.AST, source: str) -> List[Finding]:
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_catch_all(node):
            continue
        if _has_evidence(node):
            continue
        findings.append(Finding(
            "PL003", relpath, node.lineno,
            "catch-all except swallows the exception silently — log it, "
            "bump a metric, or narrow the except type",
        ))
    return findings
