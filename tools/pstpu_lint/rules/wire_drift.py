"""PL010 wire-protocol drift: formats, ops, registry, and docs must agree.

Four versioned wire formats (``PKV1``/``PKV2``/``PKC1``/``PDX1``) and the
KV-server op set (``P G E D M I H T``) are spoken by three peers — the
engine-side client, the Python server, the native C++ server — plus every
blob already sitting in a store. A new version with an encoder but no
decoder, an op the client issues but no server dispatches, or a docs table
describing last month's protocol is exactly the drift that corrupts stores
silently. Checks, all against ``tools/pstpu_lint/wire_registry.py``:

  1. every magic-shaped bytes literal (``P??<digit>``) observed in
     ``kv_offload/``+``disagg/`` is registered — an unregistered magic is
     a new wire version nobody decided the lineage of;
  2. every observed magic has BOTH an encoder occurrence (used in
     ``struct.pack``/bytes construction) and a decoder occurrence (used in
     an ``==``/``!=``/``in`` comparison) — both directions, per the
     version-tag contract; retired formats must have no encoder;
  3. every registered, non-retired format is actually implemented
     (observed at all);
  4. ops: every op the client issues (``_request(b"X"``) is dispatched by
     the Python server (``op == b"X"``) and vice versa, all registered,
     and the registry's per-op native coverage matches
     ``native/kv_server.cpp``'s ``case 'X':`` set;
  5. the registered key namespaces (``q8|``) appear in the key-building
     code;
  6. the generated ``docs/WIRE_FORMATS.md`` tables are fresh
     (PL004-style freshness gate — run ``python -m
     tools.pstpu_lint.gen_docs``).
"""

import ast
import os
import re
from typing import Dict, List, Set, Tuple

from tools.pstpu_lint import wire_registry as reg
from tools.pstpu_lint.core import Finding

SCAN_DIRS = ("production_stack_tpu/kv_offload", "production_stack_tpu/disagg")
PY_SERVER = "production_stack_tpu/kv_offload/server.py"
PY_CLIENT = "production_stack_tpu/kv_offload/remote.py"
NATIVE_SERVER = "native/kv_server.cpp"
REGISTRY_FILE = "tools/pstpu_lint/wire_registry.py"

_MAGIC_RE = re.compile(r"^P[A-Z]{2}\d$")


def _iter_py(project_root: str):
    for rel_dir in SCAN_DIRS:
        root = os.path.join(project_root, rel_dir)
        if not os.path.isdir(root):
            continue
        for dirpath, dirs, files in os.walk(root):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for name in sorted(files):
                if name.endswith(".py"):
                    path = os.path.join(dirpath, name)
                    rel = os.path.relpath(path, project_root).replace(
                        os.sep, "/")
                    yield rel, path


class _MagicUses(ast.NodeVisitor):
    """Classify every use of a magic literal (or a name bound to one) as
    encode-side (value construction) or decode-side (comparison)."""

    def __init__(self):
        self.aliases: Dict[str, str] = {}     # module var -> magic
        self.encode: Dict[str, List[int]] = {}
        self.decode: Dict[str, List[int]] = {}
        self.first_seen: Dict[str, int] = {}
        self._compare_depth = 0

    def _magic_of(self, node: ast.AST):
        if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
            try:
                text = node.value.decode("ascii")
            except UnicodeDecodeError:
                return None
            return text if _MAGIC_RE.match(text) else None
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id)
        return None

    def visit_Assign(self, node: ast.Assign):
        magic = self._magic_of(node.value)
        if magic is not None:
            self.first_seen.setdefault(magic, node.lineno)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.aliases[t.id] = magic
            return   # the defining assignment is neither side
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        for side in [node.left] + list(node.comparators):
            # Membership tests spell the magics inside a tuple/list/set:
            # ``blob[:4] in (b"PKV1", b"PKV2")`` is a decoder too.
            elems = (
                side.elts if isinstance(side, (ast.Tuple, ast.List, ast.Set))
                else [side]
            )
            for elem in elems:
                magic = self._magic_of(elem)
                if magic is not None:
                    self.first_seen.setdefault(magic, elem.lineno)
                    self.decode.setdefault(magic, []).append(elem.lineno)
        self._compare_depth += 1
        self.generic_visit(node)
        self._compare_depth -= 1

    def generic_visit(self, node: ast.AST):
        magic = self._magic_of(node)
        if magic is not None and self._compare_depth == 0:
            self.first_seen.setdefault(magic, node.lineno)
            self.encode.setdefault(magic, []).append(node.lineno)
            return
        super().generic_visit(node)


def _scan_ops_client(source: str) -> Dict[str, int]:
    """op byte -> line for every _request(b"X", ...) issue site."""
    out: Dict[str, int] = {}
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_request" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, bytes)
                and len(node.args[0].value) == 1):
            op = node.args[0].value.decode("ascii", "replace")
            out.setdefault(op, node.lineno)
    return out


def _scan_ops_server(source: str) -> Dict[str, int]:
    """op byte -> line for every ``op == b"X"`` dispatch comparison."""
    out: Dict[str, int] = {}
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        names = [s for s in sides if isinstance(s, ast.Name)]
        lits = [s for s in sides
                if isinstance(s, ast.Constant)
                and isinstance(s.value, bytes) and len(s.value) == 1]
        if lits and any(n.id == "op" for n in names):
            op = lits[0].value.decode("ascii", "replace")
            out.setdefault(op, lits[0].lineno)
    return out


def _scan_ops_native(path: str) -> Set[str]:
    with open(path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    return set(re.findall(r"case\s+'([A-Z])'\s*:", text))


def check_wire(project_root: str, registry_formats=None, registry_ops=None,
               docs_check: bool = True) -> List[Finding]:
    formats = reg.FORMATS if registry_formats is None else registry_formats
    ops = reg.OPS if registry_ops is None else registry_ops
    by_magic = {f.magic: f for f in formats}
    findings: List[Finding] = []

    # ---- formats -------------------------------------------------------
    all_encode: Dict[str, Tuple[str, int]] = {}
    all_decode: Dict[str, Tuple[str, int]] = {}
    observed: Dict[str, Tuple[str, int]] = {}
    sources: Dict[str, str] = {}
    for rel, path in _iter_py(project_root):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        sources[rel] = source
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue   # PL000 owns unparseable files
        uses = _MagicUses()
        uses.visit(tree)
        for magic, line in uses.first_seen.items():
            observed.setdefault(magic, (rel, line))
        for magic, lines in uses.encode.items():
            all_encode.setdefault(magic, (rel, lines[0]))
        for magic, lines in uses.decode.items():
            all_decode.setdefault(magic, (rel, lines[0]))

    for magic, (rel, line) in sorted(observed.items()):
        entry = by_magic.get(magic)
        if entry is None:
            findings.append(Finding(
                "PL010", rel, line,
                f"wire magic {magic!r} is not in the wire registry — a new "
                f"wire version needs a lineage decision; add it to "
                f"{REGISTRY_FILE} and regenerate docs/WIRE_FORMATS.md "
                f"(python -m tools.pstpu_lint.gen_docs)",
            ))
            # Still require both directions: a registry entry alone does
            # not make a half-implemented codec safe.
        enc = all_encode.get(magic)
        dec = all_decode.get(magic)
        if entry is not None and entry.retired:
            if enc is not None:
                findings.append(Finding(
                    "PL010", enc[0], enc[1],
                    f"wire magic {magic!r} is retired in the registry but "
                    f"still has an encoder here — stop producing it",
                ))
            continue
        if enc is not None and dec is None:
            findings.append(Finding(
                "PL010", enc[0], enc[1],
                f"wire magic {magic!r} has an encoder here but no decoder "
                f"anywhere in {' or '.join(SCAN_DIRS)} — blobs written in "
                f"this version can never be read back",
            ))
        if dec is not None and enc is None:
            findings.append(Finding(
                "PL010", dec[0], dec[1],
                f"wire magic {magic!r} has a decoder here but no encoder "
                f"anywhere in {' or '.join(SCAN_DIRS)} — either the "
                f"version is retired (mark it in {REGISTRY_FILE}) or the "
                f"write path was lost",
            ))
    for entry in formats:
        if not entry.retired and entry.magic not in observed:
            findings.append(Finding(
                "PL010", REGISTRY_FILE, 1,
                f"wire magic {entry.magic!r} is registered (non-retired) "
                f"but never appears in {' or '.join(SCAN_DIRS)} — retire "
                f"it or implement it",
            ))

    # ---- key namespaces ------------------------------------------------
    for ns in reg.KEY_NAMESPACES:
        token = ns.encode()
        if not any(repr(token)[1:] in src or ns in src
                   for src in sources.values()):
            findings.append(Finding(
                "PL010", REGISTRY_FILE, 1,
                f"registered key namespace {ns!r} never appears in the "
                f"key-building code under {' or '.join(SCAN_DIRS)}",
            ))

    # ---- ops -----------------------------------------------------------
    by_op = {o.op: o for o in ops}
    client_path = os.path.join(project_root, PY_CLIENT)
    server_path = os.path.join(project_root, PY_SERVER)
    client_ops: Dict[str, int] = {}
    server_ops: Dict[str, int] = {}
    if os.path.exists(client_path):
        with open(client_path, encoding="utf-8") as f:
            client_ops = _scan_ops_client(f.read())
    if os.path.exists(server_path):
        with open(server_path, encoding="utf-8") as f:
            server_ops = _scan_ops_server(f.read())
    for op, line in sorted(client_ops.items()):
        if op not in by_op:
            findings.append(Finding(
                "PL010", PY_CLIENT, line,
                f"client issues op {op!r} which is not in the wire "
                f"registry — register it (with its native-server story) "
                f"in {REGISTRY_FILE}",
            ))
        elif op not in server_ops:
            findings.append(Finding(
                "PL010", PY_CLIENT, line,
                f"client issues op {op!r} but the Python server never "
                f"dispatches it — every peer must speak every registered "
                f"op",
            ))
    for op, line in sorted(server_ops.items()):
        if op not in by_op:
            findings.append(Finding(
                "PL010", PY_SERVER, line,
                f"server dispatches op {op!r} which is not in the wire "
                f"registry — register it in {REGISTRY_FILE}",
            ))
        elif op not in client_ops:
            findings.append(Finding(
                "PL010", PY_SERVER, line,
                f"server dispatches op {op!r} but the client never issues "
                f"it — dead protocol surface (or the client-side wiring "
                f"was lost)",
            ))
    for op, entry in by_op.items():
        if client_ops and op not in client_ops and op not in server_ops:
            findings.append(Finding(
                "PL010", REGISTRY_FILE, 1,
                f"op {op!r} is registered but neither the client nor the "
                f"Python server implements it",
            ))
    native_path = os.path.join(project_root, NATIVE_SERVER)
    if os.path.exists(native_path) and by_op:
        native_ops = _scan_ops_native(native_path)
        want_native = {o.op for o in ops if o.native}
        for op in sorted(want_native - native_ops):
            findings.append(Finding(
                "PL010", NATIVE_SERVER, 1,
                f"registry marks op {op!r} native-supported but "
                f"{NATIVE_SERVER} has no case for it",
            ))
        for op in sorted((native_ops & set(by_op)) - want_native):
            findings.append(Finding(
                "PL010", NATIVE_SERVER, 1,
                f"{NATIVE_SERVER} implements op {op!r} but the registry "
                f"marks it non-native — update the registry's coverage "
                f"column (and docs/WIRE_FORMATS.md)",
            ))

    # ---- docs freshness ------------------------------------------------
    if docs_check:
        from tools.pstpu_lint import gen_docs

        for group, relpath, stale in gen_docs.check_wire_tables(
            project_root, formats=formats, ops=ops
        ):
            findings.append(Finding(
                "PL010", relpath, 1,
                f"wire docs table {group!r} is {stale}; run "
                f"python -m tools.pstpu_lint.gen_docs",
            ))
    return findings


# ------------------------------------------------------------- registration
def wants(project_root: str) -> bool:
    return os.path.isdir(os.path.join(project_root, SCAN_DIRS[0]))


def check(project_root: str) -> List[Finding]:
    return check_wire(project_root)
