"""PL011/PL012/PL013 — HTTP control-surface drift.

The router proxies, resumes, and re-routes against engine endpoints over
a private protocol: ``x-pstpu-*``/``x-slo-*``/``x-ttft-*``/``x-request-*``
headers, internal routes, shed-vs-error status semantics, and the
``pstpu`` SSE chunk payload. All of it is string literals spread over
three server implementations and two client harnesses — exactly the
cross-process drift class PL004 (metrics) and PL010 (wire magics) closed
for the other planes. Everything is checked against
``tools/pstpu_lint/http_registry.py``:

PL011 — header drift:
  1. every literal shaped like a claimed prefix must be a registered
     header (or an exact namespace filter such as ``"x-pstpu-"``);
  2. header literals are lowercase (aiohttp lookups are case-insensitive,
     greps are not);
  3. per scanned plane the registry names: every producer plane has a
     producing site (dict-literal key, ``headers[h] = ...``) and every
     consumer plane a consuming site (``.get``/``.pop``/``in``) — a
     header set by the router but read nowhere on the engine is drift;
  4. retired headers appear nowhere in code;
  5. the ``pstpu`` SSE payload keys (``toks``/``off``/``seed``) appear in
     every emitter and consumer file;
  6. the generated headers/payload/resume tables are fresh.

PL012 — route drift: every ``app.router.add_*`` registration is in the
registry for its plane and vice versa (the fake engine's parity with the
real engine rides on this); debug-gated routes sit behind the
``debug_endpoints`` config check and only those; every non-internal route
is referenced by at least one file under ``tests/``; routes table fresh.

PL013 — status-code semantics: every constant-status emit site
(``_error(<code>, ...)``, ``json_response(..., status=<code>)``,
``web.Response(status=<code>)``) in the server planes uses a registered
4xx/5xx code, carries the registry's companion headers (a 503 without
``Retry-After`` is indistinguishable from an outage — the soak
accounting and honor-retry-after clients key on it), and never emits a
client-side marker code (599); status tables fresh.

Constants are resolved project-wide (``RESUME_HEADER`` is declared in
``disagg/transfer.py`` and used on both planes), and one level of local
helper-call flow counts as consumption (``Deadline._header_float``).
Non-constant status expressions are out of scope by design — the fake
engine's fault-injected ``self.unavailable_status`` stays checkable by
its tests, not statically.
"""

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from tools.pstpu_lint import http_registry as reg
from tools.pstpu_lint.core import Finding

SCAN_DIRS = ("production_stack_tpu", "benchmarks")
EXTRA_FILES = ("tests/fake_engine.py",)
REGISTRY_FILE = "tools/pstpu_lint/http_registry.py"

# plane -> the file whose route table it owns
ROUTE_FILES = (
    ("engine", "production_stack_tpu/server/api_server.py"),
    ("router", "production_stack_tpu/router/app.py"),
    ("fake", "tests/fake_engine.py"),
)
_ADD_METHODS = {"add_get": "GET", "add_post": "POST", "add_put": "PUT",
                "add_delete": "DELETE", "add_patch": "PATCH"}
_GETTER_ATTRS = {"get", "getall", "getone", "pop"}
_STATUS_CALLEES = {"json_response", "Response", "HTTPException"}


def _plane_of(relpath: str) -> Optional[str]:
    if relpath in EXTRA_FILES:
        return "fake"
    if relpath.startswith("production_stack_tpu/router"):
        return "router"
    if relpath.startswith("benchmarks"):
        return "bench"
    if relpath.startswith("production_stack_tpu"):
        return "engine"
    return None


def _iter_py(project_root: str):
    for rel_dir in SCAN_DIRS:
        root = os.path.join(project_root, rel_dir)
        if not os.path.isdir(root):
            continue
        for dirpath, dirs, files in os.walk(root):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for name in sorted(files):
                if name.endswith(".py"):
                    path = os.path.join(dirpath, name)
                    rel = os.path.relpath(path, project_root).replace(
                        os.sep, "/")
                    yield rel, path
    for rel in EXTRA_FILES:
        path = os.path.join(project_root, rel)
        if os.path.exists(path):
            yield rel, path


def _parse(path: str) -> Optional[ast.Module]:
    try:
        with open(path, encoding="utf-8") as f:
            return ast.parse(f.read())
    except (SyntaxError, OSError):
        return None   # PL000 owns unparseable files


def _registry_line(project_root: str, needle: str) -> Tuple[str, int]:
    """Anchor registry-level findings to the entry (or line 1) of the
    registry module — that is where the fix or the decision belongs."""
    path = os.path.join(project_root, REGISTRY_FILE)
    try:
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if f'"{needle}"' in line:
                    return REGISTRY_FILE, i
    except OSError:
        pass
    return REGISTRY_FILE, 1


def _docstring_constants(tree: ast.Module) -> Set[int]:
    """id()s of Constant nodes that are docstrings/bare-string
    statements — header names in prose are not protocol sites."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Expr) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out.add(id(node.value))
    return out


def _is_claimed(text: str, prefixes) -> bool:
    low = text.lower()
    return (any(low.startswith(p) for p in prefixes)
            and " " not in text and "\n" not in text
            and all(c.isalnum() or c == "-" for c in low))


# --------------------------------------------------------------- PL011


def _header_symbols(project_root: str, headers_by_name, prefixes
                    ) -> Dict[str, str]:
    """Project-wide constant table: symbol name -> lowercase header, from
    ``NAME = "x-..."`` assignments and annotated (class) fields. Header
    constants are shared across planes by import (``RESUME_HEADER`` lives
    in disagg/transfer.py), so resolution is by name, not by module."""
    table: Dict[str, str] = {}
    for _rel, path in _iter_py(project_root):
        tree = _parse(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            target = None
            value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                target, value = node.target.id, node.value
            if target is None or not isinstance(value, ast.Constant) \
                    or not isinstance(value.value, str):
                continue
            if _is_claimed(value.value, prefixes):
                table[target] = value.value.lower()
    return table


def _local_getter_params(tree: ast.Module) -> Dict[str, Set[int]]:
    """function name -> parameter indices that flow into a ``.get(...)``
    inside its body (one level: ``Deadline._header_float``)."""
    out: Dict[str, Set[int]] = {}
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = [a.arg for a in fn.args.args]
        for call in ast.walk(fn):
            if isinstance(call, ast.Call) and \
                    isinstance(call.func, ast.Attribute) and \
                    call.func.attr in _GETTER_ATTRS and call.args and \
                    isinstance(call.args[0], ast.Name) and \
                    call.args[0].id in params:
                out.setdefault(fn.name, set()).add(
                    params.index(call.args[0].id))
    return out


class _HeaderUses(ast.NodeVisitor):
    """Classify every reference to a protocol header as producing
    (dict-literal key, subscript store), consuming (.get/.pop/``in``,
    subscript load, flow into a local getter helper), declaring (the
    constant/field definition itself), or a bare mention."""

    def __init__(self, symbols: Dict[str, str], getter_params,
                 skip_constants: Set[int], prefixes):
        self.symbols = symbols
        self.getter_params = getter_params
        self.skip = skip_constants
        self.prefixes = prefixes
        # (lowercase header, kind, line, raw literal or None)
        self.refs: List[Tuple[str, str, int, Optional[str]]] = []

    def _header_of(self, node) -> Optional[Tuple[str, Optional[str]]]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and id(node) not in self.skip \
                and _is_claimed(node.value, self.prefixes):
            return node.value.lower(), node.value
        if isinstance(node, ast.Name) and node.id in self.symbols:
            return self.symbols[node.id], None
        if isinstance(node, ast.Attribute) and node.attr in self.symbols:
            return self.symbols[node.attr], None
        return None

    def _emit(self, node, kind: str):
        got = self._header_of(node)
        if got is not None:
            self.refs.append((got[0], kind, node.lineno, got[1]))
            return True
        return False

    def visit_Assign(self, node: ast.Assign):
        if len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                self._emit(node.value, "declare"):
            return
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._emit(t.slice, "produce")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None and self._emit(node.value, "declare"):
            return
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict):
        for key in node.keys:
            if key is not None:
                self._emit(key, "produce")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _GETTER_ATTRS and node.args:
            self._emit(node.args[0], "consume")
        elif isinstance(node.func, ast.Name) and \
                node.func.id in self.getter_params:
            indices = self.getter_params[node.func.id]
            for i, arg in enumerate(node.args):
                if i in indices:
                    self._emit(arg, "consume")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        if any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            self._emit(node.left, "consume")
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if isinstance(node.ctx, ast.Load):
            self._emit(node.slice, "consume")
        self.generic_visit(node)

    def generic_visit(self, node: ast.AST):
        for child in ast.iter_child_nodes(node):
            got = self._header_of(child)
            if got is not None:
                self.refs.append((got[0], "mention", child.lineno, got[1]))
        super().generic_visit(node)


def check_headers(project_root: str, registry_headers=None,
                  docs_check: bool = True) -> List[Finding]:
    headers = reg.HEADERS if registry_headers is None else registry_headers
    by_name = {h.name: h for h in headers}
    prefixes = reg.CLAIMED_PREFIXES
    findings: List[Finding] = []
    symbols = _header_symbols(project_root, by_name, prefixes)
    # evidence[header][plane] = set of use kinds observed
    evidence: Dict[str, Dict[str, Set[str]]] = {}
    flagged: Set[Tuple[str, str]] = set()

    for rel, path in _iter_py(project_root):
        plane = _plane_of(rel)
        tree = _parse(path)
        if tree is None or plane is None:
            continue
        uses = _HeaderUses(symbols, _local_getter_params(tree),
                           _docstring_constants(tree), prefixes)
        uses.visit(tree)
        # A literal can be classified twice (e.g. a .get() arg is also a
        # direct child of the Call) — per-line dedupe keeps one finding
        # per actual source site.
        seen_case: Set[Tuple[int, str]] = set()
        seen_retired: Set[Tuple[int, str]] = set()
        for name, kind, line, raw in uses.refs:
            if raw is not None and raw != raw.lower() and \
                    (line, raw) not in seen_case:
                seen_case.add((line, raw))
                findings.append(Finding(
                    "PL011", rel, line,
                    f"mixed-case header literal {raw!r} — aiohttp lookups "
                    f"are case-insensitive but greps and dict keys are "
                    f"not; write {raw.lower()!r}"))
            if name in reg.HEADER_NAMESPACES:
                continue   # namespace filter site ("x-pstpu-" strip/fwd)
            entry = by_name.get(name)
            if entry is None:
                if (rel, name) not in flagged:
                    flagged.add((rel, name))
                    findings.append(Finding(
                        "PL011", rel, line,
                        f"header {name!r} is not in the HTTP registry "
                        f"(tools/pstpu_lint/http_registry.py) — every "
                        f"protocol header needs a registered producer/"
                        f"consumer contract"))
                continue
            if entry.retired and kind != "declare" and \
                    (line, name) not in seen_retired:
                seen_retired.add((line, name))
                findings.append(Finding(
                    "PL011", rel, line,
                    f"header {name!r} is retired in the HTTP registry "
                    f"but still referenced here"))
            evidence.setdefault(name, {}).setdefault(plane, set()).add(kind)

    for h in headers:
        if h.retired:
            continue
        seen = evidence.get(h.name, {})
        for plane in h.producers:
            if plane in reg.SCANNED_PLANES and \
                    "produce" not in seen.get(plane, set()):
                rfile, rline = _registry_line(project_root, h.name)
                findings.append(Finding(
                    "PL011", rfile, rline,
                    f"header {h.name!r} names {plane!r} as a producer "
                    f"but no site in that plane sets it — drift between "
                    f"the registry and the {plane} plane"))
        for plane in h.consumers:
            if plane in reg.SCANNED_PLANES and \
                    "consume" not in seen.get(plane, set()):
                rfile, rline = _registry_line(project_root, h.name)
                findings.append(Finding(
                    "PL011", rfile, rline,
                    f"header {h.name!r} names {plane!r} as a consumer "
                    f"but no site in that plane reads it — a header "
                    f"nobody reads is dead protocol"))

    findings.extend(_check_payload(project_root))
    if docs_check:
        findings.extend(_docs_findings(
            project_root, groups={"headers", "payload", "resume"},
            headers=registry_headers))
    return findings


def _check_payload(project_root: str) -> List[Finding]:
    """Every pstpu SSE payload emitter/consumer file speaks the field
    name and every registered key as string literals."""
    findings: List[Finding] = []
    wanted = [reg.SSE_PAYLOAD_FIELD] + [k.key for k in reg.SSE_PAYLOAD_KEYS]
    for rel in reg.SSE_PAYLOAD_EMITTERS + reg.SSE_PAYLOAD_CONSUMERS:
        path = os.path.join(project_root, rel)
        if not os.path.exists(path):
            continue
        tree = _parse(path)
        if tree is None:
            continue
        skip = _docstring_constants(tree)
        literals = {n.value for n in ast.walk(tree)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str) and id(n) not in skip}
        for key in wanted:
            if key not in literals:
                side = ("emitter" if rel in reg.SSE_PAYLOAD_EMITTERS
                        else "consumer")
                findings.append(Finding(
                    "PL011", rel, 1,
                    f"pstpu SSE payload {side} never mentions the "
                    f"registered key {key!r} — the resume protocol's "
                    f"chunk shape drifted (http_registry.SSE_PAYLOAD_*)"))
    return findings


# --------------------------------------------------------------- PL012


class _RouteUses(ast.NodeVisitor):
    """Collect (method, path, line, debug_gated) route registrations;
    gating context is any enclosing ``if`` whose test mentions
    ``debug_endpoints``."""

    def __init__(self):
        self.routes: List[Tuple[str, str, int, bool]] = []
        self._gate_depth = 0

    @staticmethod
    def _mentions_debug_gate(test: ast.AST) -> bool:
        for node in ast.walk(test):
            if isinstance(node, ast.Attribute) and \
                    node.attr == "debug_endpoints":
                return True
            if isinstance(node, ast.Name) and node.id == "debug_endpoints":
                return True
        return False

    def visit_If(self, node: ast.If):
        gated = self._mentions_debug_gate(node.test)
        if gated:
            self._gate_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if gated:
            self._gate_depth -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_Call(self, node: ast.Call):
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _ADD_METHODS and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            self.routes.append((
                _ADD_METHODS[node.func.attr], node.args[0].value,
                node.lineno, self._gate_depth > 0))
        self.generic_visit(node)


def _test_references(project_root: str) -> str:
    """Concatenated text of every test file (fake_engine.py excluded —
    a fake serving a route is not coverage of it)."""
    chunks = []
    tests = os.path.join(project_root, "tests")
    if os.path.isdir(tests):
        for dirpath, dirs, files in os.walk(tests):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for name in sorted(files):
                if name.endswith(".py") and name != "fake_engine.py":
                    try:
                        with open(os.path.join(dirpath, name),
                                  encoding="utf-8") as f:
                            chunks.append(f.read())
                    except OSError:
                        continue
    return "\n".join(chunks)


def check_routes(project_root: str, registry_routes=None,
                 docs_check: bool = True) -> List[Finding]:
    routes = reg.ROUTES if registry_routes is None else registry_routes
    findings: List[Finding] = []
    registered = {}   # (plane, method, path) -> Route
    for r in routes:
        for plane in r.planes:
            registered[(plane, r.method, r.path)] = r

    observed: Dict[Tuple[str, str, str], Tuple[int, bool]] = {}
    for plane, rel in ROUTE_FILES:
        path = os.path.join(project_root, rel)
        if not os.path.exists(path):
            continue
        tree = _parse(path)
        if tree is None:
            continue
        uses = _RouteUses()
        uses.visit(tree)
        for method, rpath, line, gated in uses.routes:
            observed[(plane, method, rpath)] = (line, gated)
            entry = registered.get((plane, method, rpath))
            if entry is None:
                findings.append(Finding(
                    "PL012", rel, line,
                    f"route {method} {rpath} is not in the HTTP registry "
                    f"for the {plane!r} plane "
                    f"(tools/pstpu_lint/http_registry.py)"))
                continue
            if gated and not entry.debug:
                findings.append(Finding(
                    "PL012", rel, line,
                    f"route {method} {rpath} is registered as always-on "
                    f"but served behind the debug_endpoints gate"))
            elif entry.debug and not gated:
                findings.append(Finding(
                    "PL012", rel, line,
                    f"route {method} {rpath} is registered as debug-only "
                    f"but served unconditionally — debug surfaces must "
                    f"sit behind the debug_endpoints config check"))

    scanned_planes = {plane for plane, rel in ROUTE_FILES
                      if os.path.exists(os.path.join(project_root, rel))}
    route_files = dict(ROUTE_FILES)
    for (plane, method, rpath), entry in registered.items():
        if plane in scanned_planes and \
                (plane, method, rpath) not in observed:
            findings.append(Finding(
                "PL012", route_files[plane], 1,
                f"registered route {method} {rpath} is not served by the "
                f"{plane!r} plane ({route_files[plane]}) — protocol "
                f"parity drift"))

    test_text = _test_references(project_root)
    for r in routes:
        if r.internal:
            continue
        needle = r.test_ref or r.path
        if needle not in test_text:
            rfile, rline = _registry_line(project_root, r.path)
            findings.append(Finding(
                "PL012", rfile, rline,
                f"route {r.method} {r.path} is referenced by no file "
                f"under tests/ — an untested surface drifts silently "
                f"(mark internal=True only for plane-to-plane hops)"))

    if docs_check:
        findings.extend(_docs_findings(project_root, groups={"routes"},
                                       routes=registry_routes))
    return findings


# --------------------------------------------------------------- PL013


def _status_sites(tree: ast.Module):
    """(code, headers-dict-keys or None, line) per constant-status emit
    site. ``headers`` is None when absent and () when present but not a
    literal dict (unverifiable — treated as satisfied)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        code = None
        if isinstance(node.func, ast.Name) and node.func.id == "_error" \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, int):
            code = node.args[0].value
        else:
            callee = None
            if isinstance(node.func, ast.Attribute):
                callee = node.func.attr
            elif isinstance(node.func, ast.Name):
                callee = node.func.id
            if callee in _STATUS_CALLEES:
                for kw in node.keywords:
                    if kw.arg == "status" and \
                            isinstance(kw.value, ast.Constant) and \
                            isinstance(kw.value.value, int):
                        code = kw.value.value
        if code is None:
            continue
        header_keys = None
        for kw in node.keywords:
            if kw.arg == "headers":
                if isinstance(kw.value, ast.Dict):
                    header_keys = tuple(
                        k.value.lower() for k in kw.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str))
                else:
                    header_keys = ()   # dynamic: can't verify statically
        yield code, header_keys, node.lineno


def check_status(project_root: str, registry_statuses=None,
                 docs_check: bool = True) -> List[Finding]:
    statuses = (reg.STATUS_CODES if registry_statuses is None
                else registry_statuses)
    by_code = {s.code: s for s in statuses}
    findings: List[Finding] = []
    for rel, path in _iter_py(project_root):
        plane = _plane_of(rel)
        if plane == "bench":
            continue   # the client plane owns the 599 marker
        tree = _parse(path)
        if tree is None:
            continue
        for code, header_keys, line in _status_sites(tree):
            if code < 400:
                continue
            entry = by_code.get(code)
            if entry is None:
                findings.append(Finding(
                    "PL013", rel, line,
                    f"status {code} is not in the HTTP registry — every "
                    f"4xx/5xx the servers emit needs registered "
                    f"semantics (tools/pstpu_lint/http_registry.py)"))
                continue
            if not entry.server_emitted:
                findings.append(Finding(
                    "PL013", rel, line,
                    f"status {code} ({entry.name}) is a client-side "
                    f"marker and must never be emitted by a server"))
                continue
            for companion in entry.companions:
                if header_keys is None or (
                        header_keys and companion not in header_keys):
                    findings.append(Finding(
                        "PL013", rel, line,
                        f"status {code} ({entry.name}) requires a "
                        f"{companion!r} response header — without it "
                        f"clients cannot tell an intentional shed from "
                        f"an outage (docs/RESILIENCE.md)"))
    if docs_check:
        findings.extend(_docs_findings(
            project_root, groups={"status", "status-semantics"},
            statuses=registry_statuses))
    return findings


# ------------------------------------------------------------ assembly


def _docs_findings(project_root: str, groups, headers=None, routes=None,
                   statuses=None) -> List[Finding]:
    from tools.pstpu_lint.gen_docs import check_http_tables

    rule = {"routes": "PL012", "status": "PL013",
            "status-semantics": "PL013"}
    return [
        Finding(rule.get(group, "PL011"), relpath, 1,
                f"generated HTTP table {group!r} is {what} — run "
                f"python -m tools.pstpu_lint.gen_docs")
        for group, relpath, what in check_http_tables(
            project_root, groups=groups, headers=headers, routes=routes,
            statuses=statuses)
    ]


def check_http(project_root: str, registry_headers=None,
               registry_routes=None, registry_statuses=None,
               docs_check: bool = True,
               parts=("headers", "routes", "status")) -> List[Finding]:
    """All three families in one pass (the tests' entry point)."""
    findings: List[Finding] = []
    if "headers" in parts:
        findings.extend(check_headers(project_root, registry_headers,
                                      docs_check))
    if "routes" in parts:
        findings.extend(check_routes(project_root, registry_routes,
                                     docs_check))
    if "status" in parts:
        findings.extend(check_status(project_root, registry_statuses,
                                     docs_check))
    return findings


def wants(project_root: str) -> bool:
    return os.path.exists(os.path.join(
        project_root, "production_stack_tpu/router/app.py"))
