"""PL009 async shared-state races: RMW across await, cross-context writes.

The router process mixes three execution contexts over one object graph:
the event loop (handlers), daemon threads (the stats scraper, service
discovery watch, spiller), and executor workers. Two race shapes this rule
catches, extending PL005's lock-name model:

  * **read-modify-write spanning an await** — in an ``async def``, a
    ``self.X`` value is read, the coroutine parks at an ``await``, and the
    stale value is written back afterwards::

        n = self.inflight          # read
        await self._relay(chunk)   # another task interleaves here
        self.inflight = n + 1      # lost update

    Flagged unless the whole span sits under ``async with <lock>``. Taint
    is one level deep: the written value must read ``self.X`` itself or a
    local assigned from an expression reading ``self.X`` before the await.

  * **cross-context unlocked mutation** — within a class that spawns
    threads (``threading.Thread(target=self._worker)`` /
    ``run_in_executor``/``asyncio.to_thread``) or mixes async methods with
    thread workers: an attribute mutated under a ``with <lock>`` somewhere
    (the class's locking discipline) but mutated elsewhere with **no**
    lock held is flagged at the unlocked site. Lock context propagates
    through the module-local call graph: a helper only ever called from
    inside ``with lock:`` blocks counts as locked (the
    ``RemoteKVClient._ensure_sock`` shape). ``__init__``/``__new__``
    writes are construction (happens-before publication) and exempt.

The fix is a lock, an ``asyncio.Lock``, or the atomic-swap idiom the
scraper uses (build ``fresh``, assign once under the lock).
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.pstpu_lint.callgraph import CallGraph, _own_statements
from tools.pstpu_lint.core import Finding

_LOCKISH = ("lock", "mutex")


def _walk_pruned(node: ast.AST):
    """ast.walk that does not descend into nested function/class/lambda
    bodies — they are separate execution contexts (a deferred lambda read
    evaluates at CALL time, not where it is written)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)
_MUTATORS = {"append", "add", "discard", "update", "pop", "clear",
             "extend", "remove", "setdefault", "popitem", "insert"}
_CTOR_NAMES = {"__init__", "__new__", "__post_init__"}


def _lock_name(expr: ast.AST) -> str:
    name = ""
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Call):
        return _lock_name(expr.func)
    low = name.lower()
    return name if any(tok in low for tok in _LOCKISH) else ""


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _mutations(node: ast.AST):
    """(attr, line) when this ONE node mutates a self.X attribute:
    assignment / aug-assignment / subscript store / mutator method call.
    Non-recursive — callers feed it every node of a pruned walk, so each
    mutation site is seen exactly once."""
    if isinstance(node, ast.Assign):
        targets = []
        for t in node.targets:
            targets.extend(
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t])
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                yield attr, node.lineno
            elif isinstance(t, ast.Subscript):
                attr = _self_attr(t.value)
                if attr is not None:
                    yield attr, node.lineno
    elif isinstance(node, ast.AugAssign):
        attr = _self_attr(node.target)
        if attr is None and isinstance(node.target, ast.Subscript):
            attr = _self_attr(node.target.value)
        if attr is not None:
            yield attr, node.lineno
    elif (isinstance(node, ast.Call)
          and isinstance(node.func, ast.Attribute)
          and node.func.attr in _MUTATORS):
        attr = _self_attr(node.func.value)
        if attr is not None:
            yield attr, node.lineno


# --------------------------------------------------------------------- RMW
class _RmwScan:
    """One async function body: self.X reads -> await -> self.X write."""

    def __init__(self, relpath: str):
        self.relpath = relpath
        self.findings: List[Finding] = []
        # attr -> line of the earliest pre-await read still "live"
        self.reads: Dict[str, int] = {}
        # local name -> self attrs its value was derived from
        self.derived: Dict[str, Set[str]] = {}
        self.awaited_since: Dict[str, int] = {}   # attr -> await line

    def _expr_attr_reads(self, expr: ast.AST) -> Set[str]:
        out: Set[str] = set()
        if isinstance(expr, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return out   # deferred body: evaluates at call time, not here
        for node in _walk_pruned(expr):
            attr = _self_attr(node)
            if attr is not None and isinstance(node.ctx, ast.Load):
                out.add(attr)
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                out |= self.derived.get(node.id, set())
        return out

    def _simple_stmt(self, stmt: ast.stmt, under_async_lock: bool) -> None:
        has_await = any(
            isinstance(n, ast.Await) for n in _walk_pruned(stmt))
        # Writes first: a write whose value depends on a pre-await read
        # of the same attr is the lost-update shape.
        if isinstance(stmt, ast.Assign) and not under_async_lock:
            for t in stmt.targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                if attr in self.awaited_since:
                    deps = self._expr_attr_reads(stmt.value)
                    if attr in deps:
                        self.findings.append(Finding(
                            "PL009", self.relpath, stmt.lineno,
                            f"self.{attr} is read before the await "
                            f"(line {self.reads.get(attr, '?')}) and "
                            f"written back after it (await at line "
                            f"{self.awaited_since[attr]}) — another "
                            f"task can interleave and the update is "
                            f"lost; hold an asyncio.Lock across the "
                            f"read-modify-write or recompute after "
                            f"the await",
                        ))
        # Record reads + derived locals.
        if isinstance(stmt, ast.Assign):
            deps = self._expr_attr_reads(stmt.value)
            for attr in deps:
                self.reads.setdefault(attr, stmt.lineno)
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    self.derived[t.id] = set(deps)
        else:
            for attr in self._expr_attr_reads(stmt):
                self.reads.setdefault(attr, stmt.lineno)
        # A write CLEARS the attr's pre-await read state: the next read
        # starts a fresh (possibly race-free) generation — without this, a
        # loop-body `self.x = self.x + n; await f()` would flag iteration
        # k+1's write against iteration k's await even though read and
        # write are adjacent.
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                attr = _self_attr(t)
                if attr is not None:
                    self.reads.pop(attr, None)
                    self.awaited_since.pop(attr, None)
        if has_await:
            for attr in self.reads:
                self.awaited_since.setdefault(attr, stmt.lineno)

    def scan(self, body: List[ast.stmt], under_async_lock: bool) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            # Compound statements: record only their HEADER expressions at
            # this level, then recurse — blanket-recording a whole loop
            # body's reads/awaits up front would order every read before
            # every await regardless of actual position.
            if isinstance(stmt, (ast.AsyncWith, ast.With)):
                locked = under_async_lock or (
                    isinstance(stmt, ast.AsyncWith) and any(
                        _lock_name(item.context_expr) for item in stmt.items)
                )
                self.scan(stmt.body, locked)
            elif isinstance(stmt, (ast.If, ast.While)):
                for attr in self._expr_attr_reads(stmt.test):
                    self.reads.setdefault(attr, stmt.lineno)
                self.scan(stmt.body, under_async_lock)
                self.scan(stmt.orelse, under_async_lock)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                for attr in self._expr_attr_reads(stmt.iter):
                    self.reads.setdefault(attr, stmt.lineno)
                self.scan(stmt.body, under_async_lock)
                self.scan(stmt.orelse, under_async_lock)
            elif isinstance(stmt, ast.Try):
                self.scan(stmt.body, under_async_lock)
                for handler in stmt.handlers:
                    self.scan(handler.body, under_async_lock)
                self.scan(stmt.orelse, under_async_lock)
                self.scan(stmt.finalbody, under_async_lock)
            else:
                self._simple_stmt(stmt, under_async_lock)


# ------------------------------------------------------- cross-context map
def _thread_targets(tree: ast.AST, graph: CallGraph) -> Set[str]:
    """Qualnames of functions handed to Thread(target=...) /
    run_in_executor / asyncio.to_thread, expanded through self-calls."""
    seeds: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        cands: List[ast.AST] = []
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    cands.append(kw.value)
        elif name == "run_in_executor" and len(node.args) >= 2:
            cands.append(node.args[1])
        elif name == "to_thread" and node.args:
            cands.append(node.args[0])
        for cand in cands:
            attr = _self_attr(cand)
            if attr is not None:
                for qual, info in graph.functions.items():
                    if qual.endswith("." + attr) or qual == attr:
                        seeds.add(qual)
            elif isinstance(cand, ast.Name) and cand.id in graph.functions:
                seeds.add(cand.id)
    # Expand through module-local calls (a worker's helpers run on the
    # worker thread too).
    frontier = list(seeds)
    while frontier:
        qual = frontier.pop()
        info = graph.functions.get(qual)
        if info is None:
            continue
        for callee, _line in info.calls:
            if callee not in seeds:
                seeds.add(callee)
                frontier.append(callee)
    return seeds


def _locked_spans(fn_node: ast.AST) -> List[Tuple[int, int, str]]:
    """(start, end, lockname) line spans of sync ``with <lock>`` blocks."""
    spans = []
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lock = _lock_name(item.context_expr)
                if lock:
                    end = getattr(node, "end_lineno", node.lineno)
                    spans.append((node.lineno, end, lock))
    return spans


def _line_locked(spans, line: int) -> Optional[str]:
    for start, end, lock in spans:
        if start <= line <= end:
            return lock
    return None


def _always_called_locked(qual: str, graph: CallGraph,
                          lock_spans: Dict[str, list]) -> bool:
    """True when every module-local call site of ``qual`` sits inside a
    with-lock span (the helper-under-lock shape)."""
    sites = []
    for caller, info in graph.functions.items():
        for callee, line in info.calls:
            if callee == qual:
                sites.append((caller, line))
    if not sites:
        return False
    return all(
        _line_locked(lock_spans.get(caller, []), line) is not None
        for caller, line in sites
    )


def _only_called_from_ctor(qual: str, graph: CallGraph) -> bool:
    """True when every module-local call site of ``qual`` is inside a
    constructor — the ``self._load()``-from-``__init__`` shape. The object
    is not published yet (happens-before), so its writes are exempt like
    the constructor's own."""
    sites = []
    for caller, info in graph.functions.items():
        for callee, _line in info.calls:
            if callee == qual:
                sites.append(caller)
    if not sites:
        return False
    return all(s.rsplit(".", 1)[-1] in _CTOR_NAMES for s in sites)


def check(relpath: str, tree: ast.AST, source: str) -> List[Finding]:
    graph = CallGraph(tree)
    findings: List[Finding] = []

    # ---- RMW across await ---------------------------------------------
    for qual, info in graph.functions.items():
        if not info.is_async:
            continue
        scan = _RmwScan(relpath)
        scan.scan(info.node.body, under_async_lock=False)
        findings.extend(scan.findings)

    # ---- cross-context unlocked mutation ------------------------------
    threaded = _thread_targets(tree, graph)
    async_ctx = set(graph.async_context())
    lock_spans = {
        qual: _locked_spans(info.node)
        for qual, info in graph.functions.items()
    }
    # Per class: attr -> [(qual, line, lock-or-None)]
    per_class: Dict[str, Dict[str, list]] = {}
    spawns_threads: Set[str] = set()
    for qual, info in graph.functions.items():
        cls = info.enclosing_class
        if cls is None:
            continue
        if qual in threaded:
            spawns_threads.add(cls)
        if qual.rsplit(".", 1)[-1] in _CTOR_NAMES:
            continue
        if _only_called_from_ctor(qual, graph):
            continue
        spans = lock_spans.get(qual, [])
        inherited = (
            "(callers)" if _always_called_locked(qual, graph, lock_spans)
            else None
        )
        for node in _own_statements(info.node):
            if not isinstance(node, ast.stmt):
                continue
            for attr, line in _mutations(node):
                lock = _line_locked(spans, line) or inherited
                per_class.setdefault(cls, {}).setdefault(attr, []).append(
                    (qual, line, lock))
    for cls, attrs in per_class.items():
        # Only classes that actually spawn threads have a cross-THREAD
        # surface; async-only interleaving is the RMW check's job (a
        # coroutine cannot preempt a sync mutation mid-statement).
        if cls not in spawns_threads:
            continue
        for attr, sites in attrs.items():
            locked_sites = [s for s in sites if s[2] is not None]
            unlocked = [s for s in sites if s[2] is None]
            if not locked_sites or not unlocked:
                continue
            # The discipline exists (a locked mutation) and is violated
            # (an unlocked one elsewhere). Same-function pairs are still
            # races when the class mixes contexts.
            lock = locked_sites[0][2]
            for qual, line, _none in unlocked:
                findings.append(Finding(
                    "PL009", relpath, line,
                    f"self.{attr} is mutated under {lock} elsewhere in "
                    f"{cls} (e.g. line {locked_sites[0][1]}) but mutated "
                    f"here without the lock — cross-thread lost update; "
                    f"take the lock or swap atomically",
                ))
    return findings
