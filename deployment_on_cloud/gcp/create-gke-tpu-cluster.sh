#!/bin/bash
# Create a GKE cluster with a TPU v5e node pool sized for the serving stack
# (cloud-deploy parity with reference deployment_on_cloud/gcp, targeting TPU
# node pools instead of GPU ones).
set -euo pipefail

PROJECT="${PROJECT:?set PROJECT}"
CLUSTER="${CLUSTER:-pstpu-serving}"
REGION="${REGION:-us-west4}"
ZONE="${ZONE:-us-west4-a}"
# ct5lp-hightpu-1t = 1 v5e chip/node; ct5lp-hightpu-4t = 2x2 slice/node.
TPU_MACHINE="${TPU_MACHINE:-ct5lp-hightpu-4t}"
TPU_TOPOLOGY="${TPU_TOPOLOGY:-2x2}"
TPU_NODES="${TPU_NODES:-2}"

gcloud container clusters create "$CLUSTER" \
  --project "$PROJECT" --zone "$ZONE" \
  --num-nodes 1 --machine-type e2-standard-8 \
  --release-channel regular

gcloud container node-pools create tpu-pool \
  --project "$PROJECT" --zone "$ZONE" --cluster "$CLUSTER" \
  --machine-type "$TPU_MACHINE" \
  --tpu-topology "$TPU_TOPOLOGY" \
  --num-nodes "$TPU_NODES" \
  --enable-autoscaling --min-nodes 1 --max-nodes 4

gcloud container clusters get-credentials "$CLUSTER" \
  --project "$PROJECT" --zone "$ZONE"

echo "Cluster ready. Deploy the stack with:"
echo "  helm install stack ./helm -f helm/examples/values-01-minimal-example.yaml"
