// Shared KV cache server — the cache-server tier of the stack.
//
// The reference deploys `lmcache_experimental_server` as a standalone pod
// (reference helm/templates/deployment-cache-server.yaml:29-51) that engines
// reach over TCP (LMCACHE_REMOTE_URL). This is the TPU stack's native
// equivalent: a C++ blob store keyed by KV block hashes, LRU-bounded, with
// the length-prefixed protocol documented in
// production_stack_tpu/kv_offload/remote.py:
//
//   request:  op(1) | key_len(u32 LE) | key | val_len(u64 LE) | val
//   response: status(1: 0=ok, 1=missing, 2=error) | val_len(u64 LE) | val
//   ops: 'P' put, 'G' get, 'E' exists, 'T' stats (JSON)
//
// Thread-per-connection (engine pods hold one connection each; connection
// count is small), one global mutex around the store (operations are
// memcpy-bound; the mutex is held only for map/LRU bookkeeping and the
// value move, not for socket IO).
//
// Build: make -C native   (produces build/kv_server)
// Run:   kv_server [--port 8200] [--max-bytes 34359738368]

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <list>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>
#include <cstdio>
#include <csignal>

namespace {

struct Store {
  struct Entry {
    std::string value;
    std::list<std::string>::iterator lru_it;
  };

  std::mutex mu;
  std::unordered_map<std::string, Entry> map;
  std::list<std::string> lru;  // front = most recent
  size_t bytes = 0;
  size_t max_bytes;
  std::atomic<uint64_t> hits{0}, misses{0}, stores{0}, evictions{0};

  explicit Store(size_t max) : max_bytes(max) {}

  void put(const std::string& key, std::string&& value) {
    std::lock_guard<std::mutex> lock(mu);
    auto it = map.find(key);
    if (it != map.end()) {
      bytes -= it->second.value.size();
      lru.erase(it->second.lru_it);
      map.erase(it);
    }
    bytes += value.size();
    lru.push_front(key);
    map.emplace(key, Entry{std::move(value), lru.begin()});
    stores++;
    while (bytes > max_bytes && !lru.empty()) {
      const std::string& victim = lru.back();
      auto vit = map.find(victim);
      if (vit != map.end()) {
        bytes -= vit->second.value.size();
        map.erase(vit);
      }
      lru.pop_back();
      evictions++;
    }
  }

  bool get(const std::string& key, std::string* out) {
    std::lock_guard<std::mutex> lock(mu);
    auto it = map.find(key);
    if (it == map.end()) {
      misses++;
      return false;
    }
    lru.erase(it->second.lru_it);
    lru.push_front(key);
    it->second.lru_it = lru.begin();
    *out = it->second.value;  // copy so IO happens outside the lock
    hits++;
    return true;
  }

  bool exists(const std::string& key) {
    std::lock_guard<std::mutex> lock(mu);
    return map.find(key) != map.end();
  }

  std::string stats_json() {
    std::lock_guard<std::mutex> lock(mu);
    char buf[512];
    snprintf(buf, sizeof(buf),
             "{\"entries\": %zu, \"bytes\": %zu, \"max_bytes\": %zu, "
             "\"hits\": %llu, \"misses\": %llu, \"stores\": %llu, "
             "\"evictions\": %llu}",
             map.size(), bytes, max_bytes,
             (unsigned long long)hits.load(),
             (unsigned long long)misses.load(),
             (unsigned long long)stores.load(),
             (unsigned long long)evictions.load());
    return buf;
  }
};

bool recv_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = send(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_response(int fd, uint8_t status, const std::string& payload) {
  uint64_t vlen = payload.size();
  char header[9];
  header[0] = static_cast<char>(status);
  memcpy(header + 1, &vlen, 8);  // little-endian host assumed (x86/arm64)
  if (!send_all(fd, header, 9)) return false;
  if (vlen && !send_all(fd, payload.data(), vlen)) return false;
  return true;
}

constexpr size_t kMaxKeyLen = 1 << 16;
constexpr size_t kMaxValLen = 1ULL << 32;  // 4 GiB per block is already absurd

void serve_connection(int fd, Store* store) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    char op;
    uint32_t klen;
    uint64_t vlen;
    if (!recv_exact(fd, &op, 1)) break;
    if (!recv_exact(fd, &klen, 4)) break;
    if (klen > kMaxKeyLen) break;
    std::string key(klen, '\0');
    if (klen && !recv_exact(fd, key.data(), klen)) break;
    if (!recv_exact(fd, &vlen, 8)) break;
    if (vlen > kMaxValLen) break;
    std::string val(vlen, '\0');
    if (vlen && !recv_exact(fd, val.data(), vlen)) break;

    bool ok = true;
    switch (op) {
      case 'P':
        store->put(key, std::move(val));
        ok = send_response(fd, 0, "");
        break;
      case 'G': {
        std::string out;
        if (store->get(key, &out)) {
          ok = send_response(fd, 0, out);
        } else {
          ok = send_response(fd, 1, "");
        }
        break;
      }
      case 'E':
        ok = send_response(fd, store->exists(key) ? 0 : 1, "");
        break;
      case 'T':
        ok = send_response(fd, 0, store->stats_json());
        break;
      default:
        ok = send_response(fd, 2, "");
        break;
    }
    if (!ok) break;
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = 8200;
  size_t max_bytes = 32ULL << 30;
  for (int i = 1; i + 1 < argc; i += 2) {
    if (!strcmp(argv[i], "--port")) port = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--max-bytes")) max_bytes = strtoull(argv[i + 1], nullptr, 10);
  }
  signal(SIGPIPE, SIG_IGN);

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(lfd, 128) != 0) {
    perror("listen");
    return 1;
  }
  fprintf(stderr, "kv_server listening on :%d (max %zu bytes)\n", port,
          max_bytes);

  Store store(max_bytes);
  for (;;) {
    int cfd = accept(lfd, nullptr, nullptr);
    if (cfd < 0) continue;
    std::thread(serve_connection, cfd, &store).detach();
  }
}
