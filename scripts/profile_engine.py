"""Instrument the engine loop during the bench workload: log every dispatch
(kind, rows, K/T, device ms) and the host-side gap between dispatches.
Run: PYTHONPATH=/root/.axon_site:/root/repo python scripts/profile_engine.py
"""
import asyncio
import time

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.engine import ServingEngine
from production_stack_tpu.engine.sampling import SamplingParams

import bench


async def main():
    cfg = EngineConfig(
        model="llama-1b", max_model_len=1024, block_size=16,
        max_num_seqs=16, max_num_batched_tokens=1024,
    )
    engine = ServingEngine(cfg)
    runner = engine.runner

    log = []
    orig = runner.execute

    def traced(batch, step):
        t0 = time.perf_counter()
        out = orig(batch, step)
        t1 = time.perf_counter()
        log.append((
            t0, t1, batch.kind, len(batch.seqs),
            batch.num_steps if batch.kind == "decode" else max(batch.chunk_lens),
        ))
        return out

    runner.execute = traced

    await engine.start()
    try:
        res = await bench._bench_engine(engine, 16, 2, 600, 64)
    finally:
        await engine.stop()
    print(res)

    print(f"{'kind':8} {'rows':4} {'K/T':5} {'dev_ms':8} {'gap_ms':8}")
    prev_end = None
    tot_dev = tot_gap = 0.0
    for t0, t1, kind, rows, kt in log:
        gap = (t0 - prev_end) * 1000 if prev_end else 0.0
        dev = (t1 - t0) * 1000
        tot_dev += dev
        tot_gap += gap
        print(f"{kind:8} {rows:4} {kt:5} {dev:8.1f} {gap:8.1f}")
        prev_end = t1
    print(f"dispatches={len(log)} total_device={tot_dev:.0f} ms "
          f"total_gap={tot_gap:.0f} ms")


if __name__ == "__main__":
    asyncio.run(main())
