"""Decompose the serving dispatch wall time: per-step compute vs per-dispatch
fixed cost, in isolation (no router/client processes competing for the one
host core).

Times the runner's REAL jitted dispatches at the bench's steady-state
shapes: decode K in {1, 8, 32} with cached/fresh windows, the windowed
continuation prefill, and gather_window alone. Prints one JSON line per
measurement.

Run: python scripts/profile_fixed_cost.py [--attn-impl window|paged]
"""

import argparse
import json
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-1b")
    ap.add_argument("--ctx-tokens", type=int, default=1500)
    ap.add_argument("--rows", type=int, default=16)
    ap.add_argument("--reps", type=int, default=8)
    ap.add_argument("--attn-impl", default="auto")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.runner import NUM_SCALARS, ModelRunner
    from production_stack_tpu.models.config import resolve_model_config
    from production_stack_tpu.ops.attention import gather_window
    from production_stack_tpu.parallel.mesh import make_mesh
    from production_stack_tpu.utils import window_mb_bucket

    cfg = EngineConfig(
        model=args.model, max_model_len=8192, block_size=16,
        max_num_seqs=args.rows, max_num_batched_tokens=4096,
        attn_impl=args.attn_impl,
    )
    mc = resolve_model_config(args.model)
    runner = ModelRunner(cfg, mc, make_mesh(1, 1, 1))
    bs = cfg.block_size
    b = args.rows
    blocks_per_row = -(-args.ctx_tokens // bs)
    mb = runner._decode_mb(blocks_per_row)
    print(json.dumps({"attn_impl": runner.attn_impl, "b": b, "mb": mb,
                      "ctx": args.ctx_tokens,
                      "num_kv_blocks": runner.num_kv_blocks}))
    assert b * blocks_per_row < runner.num_kv_blocks - 1, "pool too small"

    def packed_decode():
        packed = np.zeros((NUM_SCALARS * b + b * mb,), np.int32)
        sc = packed[: NUM_SCALARS * b].reshape(NUM_SCALARS, b)
        bt = packed[NUM_SCALARS * b:].reshape(b, mb)
        sc[0, :] = 1
        sc[1, :] = args.ctx_tokens            # pos0
        sc[2, :] = 10**6                      # budget: never exhausts
        sc[6, :] = -1
        sc[11, :] = -1  # no token chain
        sc.view(np.float32)[7, :] = 1.0
        for i in range(b):
            bt[i, :blocks_per_row] = 1 + i * blocks_per_row + np.arange(
                blocks_per_row, dtype=np.int32
            )
        return packed

    win = None

    def one_decode(k, cached):
        nonlocal win
        dummy = jnp.zeros((1, 1, 1, 1, 1), runner.dtype)
        use_cached = bool(cached and win is not None)
        out = runner._decode(
            runner.params, jnp.asarray(packed_decode()),
            runner.kv_k, runner.kv_v,
            win[0] if use_cached else dummy,
            win[1] if use_cached else dummy,
            jnp.zeros((1, 1), jnp.int32), runner._zero_last,
            b=b, mb=mb, num_steps=k, use_cached_window=use_cached,
            has_penalties=False, logprobs_k=0,
        )
        toks, runner.kv_k, runner.kv_v = out[0], out[1], out[2]
        if runner.attn_impl == "window":
            win = (out[3], out[4])
        else:
            win = None
        np.asarray(toks)  # the serving path's device->host sync

    def time_decode(k, cached, label):
        times = []
        for rep in range(args.reps + 2):
            t0 = time.monotonic()
            one_decode(k, cached)
            if rep >= 2:
                times.append(time.monotonic() - t0)
        ms = 1000 * float(np.median(times))
        print(json.dumps({
            "measure": label, "k": k, "ms": round(ms, 1),
            "ms_per_step": round(ms / k, 2),
            "tok_s_equiv": round(b * k / (ms / 1000)),
        }))
        return ms

    time_decode(1, cached=False, label="decode_fresh_k1")
    time_decode(32, cached=False, label="decode_fresh_k32")
    cached = runner.attn_impl == "window"
    m1 = time_decode(1, cached=cached, label="decode_steady_k1")
    m8 = time_decode(8, cached=cached, label="decode_steady_k8")
    m32 = time_decode(32, cached=cached, label="decode_steady_k32")
    per_step = (m32 - m8) / 24
    print(json.dumps({
        "measure": "decode_decomposition",
        "per_step_ms": round(per_step, 2),
        "fixed_ms": round(m8 - 8 * per_step, 1),
        "k1_ms": round(m1, 1),
    }))

    # gather_window alone (per fresh-batch window rebuild / windowed
    # prefill gather).
    bt = jnp.asarray(
        packed_decode()[NUM_SCALARS * b:].reshape(b, mb)
    )
    g = jax.jit(lambda kk, vv, t: gather_window(kk, vv, t, bs))
    for _ in range(3):
        t0 = time.monotonic()
        wk2, wv2 = g(runner.kv_k, runner.kv_v, bt)
        jax.block_until_ready(wk2)
        gw = time.monotonic() - t0
    gbytes = 2 * wk2.size * wk2.dtype.itemsize / 1e9
    print(json.dumps({"measure": "gather_window", "ms": round(1000 * gw, 1),
                      "gbytes": round(gbytes, 2),
                      "gb_s": round(gbytes / gw, 1)}))
    del wk2, wv2

    # Windowed continuation prefill at the bench's cache-hit round shape.
    rows, t_chunk = 8, 256
    pmb = window_mb_bucket(blocks_per_row, cfg.max_blocks_per_seq)
    packed = np.zeros(
        (NUM_SCALARS * rows + rows * pmb + rows * t_chunk,), np.int32
    )
    sc = packed[: NUM_SCALARS * rows].reshape(NUM_SCALARS, rows)
    btp = packed[
        NUM_SCALARS * rows: NUM_SCALARS * rows + rows * pmb
    ].reshape(rows, pmb)
    sc[0, :] = args.ctx_tokens
    sc[1, :] = 120
    sc[6, :] = -1
    sc.view(np.float32)[7, :] = 1.0
    for i in range(rows):
        btp[i, :blocks_per_row] = 1 + i * blocks_per_row + np.arange(
            blocks_per_row, dtype=np.int32
        )
    times = []
    for rep in range(args.reps + 2):
        t0 = time.monotonic()
        out = runner._prefill(
            runner.params, jnp.asarray(packed), runner.kv_k, runner.kv_v,
            jnp.zeros((1, 1), jnp.int32),
            b=rows, t=t_chunk, mb=pmb, has_window=True,
            b_max=runner._b_max,
            has_penalties=False, logprobs_k=0,
        )
        runner.kv_k, runner.kv_v = out[1], out[2]
        np.asarray(out[0])
        if rep >= 2:
            times.append(time.monotonic() - t0)
    print(json.dumps({"measure": "prefill_windowed", "rows": rows,
                      "t": t_chunk, "mb": pmb,
                      "ms": round(1000 * float(np.median(times)), 1)}))


if __name__ == "__main__":
    main()
