"""Prefill dispatch profiling at realistic chunked shapes on the real TPU.
Run: PYTHONPATH=/root/.axon_site:/root/repo python scripts/profile_prefill.py
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.models import get_model_fns
from production_stack_tpu.models.config import resolve_model_config
from production_stack_tpu.ops.attention import gather_window

MODEL = "llama-1b"
BS = 16


def timed(fn, *args, n=5, **kw):
    """args[1] (token ids) is varied per call to defeat any dispatch-level
    result caching in the device tunnel; each call is blocked individually
    so per-dispatch latency is real."""
    out = fn(args[0], args[1], *args[2:], **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for i in range(n):
        out = fn(args[0], args[1] + i + 1, *args[2:], **kw)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1000, out


def main():
    mc = resolve_model_config(MODEL)
    init_fn, forward, logits_fn = get_model_fns(mc)
    params = jax.device_put(init_fn(mc, jax.random.PRNGKey(0), jnp.bfloat16))
    nl, hkv, dh = mc.num_layers, mc.num_kv_heads, mc.head_dim_

    for b, t, hist in [(8, 64, 1024), (8, 128, 1024), (8, 512, 2048),
                       (4, 512, 4096), (8, 512, 0), (1, 4096, 0)]:
        nslots = max(b * (hist + t) + BS, BS * 2)
        kv_k = jnp.zeros((nl, hkv, nslots, dh), jnp.bfloat16)
        kv_v = jnp.zeros((nl, hkv, nslots, dh), jnp.bfloat16)
        mb = max(1, (hist + t) // BS)
        bt = np.zeros((b, mb), np.int32)
        for i in range(b):
            bt[i] = np.arange(1 + i * mb, 1 + (i + 1) * mb)
        bt = jnp.asarray(bt)
        toks = jnp.zeros((b, t), jnp.int32)
        pos = hist + jnp.broadcast_to(jnp.arange(t)[None], (b, t))
        lens = jnp.full((b,), t, jnp.int32)

        if hist > 0:
            def full(params, toks, pos, lens, kv_k, kv_v, bt):
                wk, wv = gather_window(kv_k, kv_v, bt, BS)
                wl = jnp.full((b,), hist, jnp.int32)
                h, kn, vn = forward(params, mc, toks, pos, lens, wk, wv, wl)
                lg = logits_fn(params, mc, h[jnp.arange(b), lens - 1])
                return lg, kn, vn

            gw = jax.jit(lambda k, v, tb: gather_window(k + 0.0, v, tb, BS))
            gms, w = timed(gw, kv_k, kv_v, bt)
            wbytes = sum(x.size * x.dtype.itemsize for x in w)
            fms, _ = timed(jax.jit(full), params, toks, pos, lens,
                           kv_k, kv_v, bt)
            print(f"b={b} t={t} hist={hist}: full={fms:7.1f} ms "
                  f"gather={gms:6.1f} ms win={wbytes/2**30:.2f} GiB "
                  f"-> {b*t/fms*1000:.0f} tok/s")
        else:
            def nowin(params, toks, pos, lens):
                h, kn, vn = forward(params, mc, toks, pos, lens)
                lg = logits_fn(params, mc, h[jnp.arange(b), lens - 1])
                return lg, kn, vn

            fms, _ = timed(jax.jit(nowin), params, toks, pos, lens)
            print(f"b={b} t={t} hist={hist}: full={fms:7.1f} ms "
                  f"-> {b*t/fms*1000:.0f} tok/s")


if __name__ == "__main__":
    main()
