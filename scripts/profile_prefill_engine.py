"""Cold-prefill dispatch timeline: 8 distinct 1024-token prompts, no prefix
sharing. Shows where stack-level TTFT goes.
Run: PYTHONPATH=/root/.axon_site:/root/repo python scripts/profile_prefill_engine.py
"""
import asyncio
import time

import numpy as np

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.engine import ServingEngine
from production_stack_tpu.engine.sampling import SamplingParams


async def main():
    cfg = EngineConfig(
        model="llama-1b", max_model_len=8192, block_size=16,
        max_num_seqs=16, enable_prefix_caching=False,
    )
    engine = ServingEngine(cfg)
    runner = engine.runner
    log = []
    orig = runner.execute

    def traced(batch, step):
        t0 = time.perf_counter()
        out = orig(batch, step)
        t1 = time.perf_counter()
        log.append((batch.kind, len(batch.seqs),
                    batch.num_steps if batch.kind == "decode"
                    else max(batch.chunk_lens), (t1 - t0) * 1000))
        return out

    runner.execute = traced
    await engine.start()

    rng = np.random.default_rng(0)

    async def one(i, toks):
        async for _ in engine.generate(
            prompt_token_ids=toks,
            sampling=SamplingParams(temperature=0.0, max_tokens=4,
                                    ignore_eos=True),
        ):
            pass

    for trial in range(3):
        log.clear()
        toks = [rng.integers(10, 30000, 1024).tolist() for _ in range(8)]
        t0 = time.perf_counter()
        await asyncio.gather(*[one(i, t) for i, t in enumerate(toks)])
        dt = time.perf_counter() - t0
        if trial == 0:
            continue  # compile pass
        print(f"trial {trial}: 8x1024 prefill+4tok in {dt*1000:.0f} ms "
              f"-> prefill {8*1024/dt:.0f} tok/s")
        for kind, rows, kt, ms in log:
            print(f"  {kind:8} rows={rows} T/K={kt:4} {ms:7.1f} ms")
    await engine.stop()


if __name__ == "__main__":
    asyncio.run(main())
