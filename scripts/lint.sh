#!/usr/bin/env bash
# One-command static analysis gate: ruff (error-tier) + scoped mypy +
# pstpu-lint. CI runs this in the `lint` job; locally it degrades
# gracefully — ruff/mypy are optional extras (pip install -e .[lint]) and
# are skipped with a warning when absent, while the stdlib-only pstpu-lint
# pass always runs. Pass --require-tools (CI does) to make a missing
# ruff/mypy a failure instead of a skip.
set -uo pipefail
cd "$(dirname "$0")/.."

REQUIRE_TOOLS=0
[ "${1:-}" = "--require-tools" ] && REQUIRE_TOOLS=1

# GitHub annotations render findings inline on the PR diff.
FORMAT=text
[ "${GITHUB_ACTIONS:-}" = "true" ] && FORMAT=github

fail=0

if python -m ruff --version >/dev/null 2>&1; then
    echo "== ruff (error-tier rules; [tool.ruff.lint] in pyproject.toml)"
    python -m ruff check production_stack_tpu tools benchmarks || fail=1
else
    echo "== ruff not installed — skipping (pip install -e .[lint])"
    [ "$REQUIRE_TOOLS" = 1 ] && fail=1
fi

if python -m mypy --version >/dev/null 2>&1; then
    # Scope: the router + disagg + kv_offload tiers (the asyncio data
    # plane and the wire-protocol codecs, where type confusion turns into
    # 3am pages or corrupted stores) + server/ (the engine API surface —
    # the other half of the HTTP contract PL011-PL013 lint; a handler
    # returning the wrong shape is a protocol break, not a unit bug).
    # Widen as annotations land; config and per-flag rationale live under
    # [tool.mypy] in pyproject.toml.
    echo "== mypy (scoped: router/ + disagg/ + kv_offload/ + server/)"
    python -m mypy production_stack_tpu/router production_stack_tpu/disagg \
        production_stack_tpu/kv_offload production_stack_tpu/server \
        || fail=1
else
    echo "== mypy not installed — skipping (pip install -e .[lint])"
    [ "$REQUIRE_TOOLS" = 1 ] && fail=1
fi

echo "== pstpu-lint (tools/pstpu_lint; docs/LINTING.md has the catalogue)"
python -m tools.pstpu_lint production_stack_tpu/ tools/ benchmarks/ \
    --format "$FORMAT" || fail=1

exit $fail
