"""Paged-vs-window decode on the real TPU: llama-3b (head_dim 128) at long
max_model_len. Records the Pallas-vs-XLA(window) comparison VERDICT r2 asked
for. Run: PYTHONPATH=/root/.axon_site:/root/repo python scripts/bench_paged_tpu.py [impl ...]
"""
import asyncio
import sys
import time

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.engine import ServingEngine
from production_stack_tpu.engine.sampling import SamplingParams


async def run(attn_impl, model="llama-3b", users=8, max_tokens=64,
              prompt_reps=40, max_model_len=8192):
    cfg = EngineConfig(
        model=model, max_model_len=max_model_len, block_size=16,
        max_num_seqs=users, max_num_batched_tokens=2048,
        attn_impl=attn_impl,
    )
    eng = ServingEngine(cfg)
    await eng.start()
    sampling = SamplingParams(temperature=0.0, max_tokens=max_tokens,
                              ignore_eos=True)
    base = "The quick brown fox jumps over the lazy dog. " * prompt_reps

    async def one(i, mt):
        sp = SamplingParams(temperature=0.0, max_tokens=mt, ignore_eos=True)
        n = 0
        async for o in eng.generate(prompt=base + f" user {i}.", sampling=sp):
            n = o.num_output_tokens
        return n

    # warmup (same shapes)
    await asyncio.gather(*[one(i, max_tokens) for i in range(users)])
    t0 = time.perf_counter()
    total = sum(await asyncio.gather(*[one(i, max_tokens) for i in range(users)]))
    dt = time.perf_counter() - t0
    print(f"{attn_impl}: {total} tokens in {dt:.2f}s -> {total/dt:.0f} tok/s "
          f"(model={model}, len={max_model_len}, kv_blocks={eng.runner.num_kv_blocks})")
    await eng.stop()
    return total / dt


if __name__ == "__main__":
    impls = sys.argv[1:] or ["paged", "window"]
    for impl in impls:
        asyncio.run(run(impl))
