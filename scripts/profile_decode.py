"""Profile the fused decode dispatch component-by-component on the real TPU.

Answers VERDICT r2 weak #1: where do the ~32 ms/step go at llama-1b, B=16?
Run: python scripts/profile_decode.py
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.sampling import sample_tokens
from production_stack_tpu.models import get_model_fns
from production_stack_tpu.models.config import resolve_model_config
from production_stack_tpu.ops.attention import gather_window

MODEL = "llama-1b"
B = 16
S = 1024          # live context per sequence
K = 32            # fused steps
BS = 16           # block size


def timed(fn, *args, n=10, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n * 1000, out


def main():
    mc = resolve_model_config(MODEL)
    init_fn, forward, logits_fn = get_model_fns(mc)
    params = init_fn(mc, jax.random.PRNGKey(0), jnp.bfloat16)
    params = jax.device_put(params)
    nl, hkv, dh = mc.num_layers, mc.num_kv_heads, mc.head_dim_
    nslots = B * S + BS
    kv_k = jnp.zeros((nl, hkv, nslots, dh), jnp.bfloat16)
    kv_v = jnp.zeros((nl, hkv, nslots, dh), jnp.bfloat16)
    mb = S // BS
    bt = np.zeros((B, mb), np.int32)
    for i in range(B):
        bt[i] = np.arange(1 + i * mb, 1 + (i + 1) * mb)
    bt = jnp.asarray(bt * 0 + bt)  # device
    nbytes = lambda *arrs: sum(a.size * a.dtype.itemsize for a in arrs)

    pbytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    print(f"model={MODEL} params={pbytes/2**30:.2f} GiB "
          f"kv_live={nbytes(kv_k, kv_v)/2**30:.2f} GiB B={B} S={S} K={K}")

    # 1. gather_window alone
    gw = jax.jit(lambda k, v, t: gather_window(k, v, t, BS))
    ms, (wk, wv) = timed(gw, kv_k, kv_v, bt)
    wbytes = nbytes(wk, wv)
    print(f"gather_window: {ms:8.2f} ms  window={wbytes/2**30:.2f} GiB "
          f"({wbytes/ms*1e3/2**30:.0f} GiB/s eff)")

    win_len = jnp.full((B,), S, jnp.int32)
    toks = jnp.zeros((B, 1), jnp.int32)
    pos = jnp.full((B, 1), S, jnp.int32)
    ones = jnp.ones((B,), jnp.int32)
    ring_k = jnp.zeros((nl, hkv, B, K, dh), jnp.bfloat16)
    ring_v = jnp.zeros((nl, hkv, B, K, dh), jnp.bfloat16)
    ring_pos = jnp.full((B, K), 2**30, jnp.int32)

    # 2. single forward (1 token, with window + ring)
    fwd = jax.jit(lambda p, t, po, wk, wv, rk, rv, rp: forward(
        p, mc, t, po, ones, wk, wv, win_len, rk, rv, rp))
    ms, (hidden, k_new, v_new) = timed(
        fwd, params, toks, pos, wk, wv, ring_k, ring_v, ring_pos)
    need = pbytes - 2 * mc.vocab_size * mc.hidden_size + wbytes
    print(f"forward(1tok): {ms:8.2f} ms  min_traffic={need/2**30:.2f} GiB "
          f"-> {need/ms*1e3/2**30:.0f} GiB/s eff")

    # 3. logits
    lg = jax.jit(lambda p, h: logits_fn(p, mc, h[:, 0]))
    ms, logits = timed(lg, params, hidden)
    hb = 2 * mc.vocab_size * mc.hidden_size
    print(f"logits:        {ms:8.2f} ms  head={hb/2**30:.2f} GiB "
          f"-> {hb/ms*1e3/2**30:.0f} GiB/s eff")

    # 4. sampling
    temps = jnp.ones((B,), jnp.float32)
    tk = jnp.full((B,), -1, jnp.int32)
    tp = jnp.ones((B,), jnp.float32)
    seeds = jnp.arange(B, dtype=jnp.uint32)
    ms, _ = timed(sample_tokens, logits, temps, tk, tp, seeds)
    print(f"sample:        {ms:8.2f} ms")

    # 4b. greedy-only argmax
    ms, _ = timed(jax.jit(lambda l: jnp.argmax(l, -1)), logits)
    print(f"argmax only:   {ms:8.2f} ms")

    # 5. full fused scan (forward+logits+sample+ring update) x K
    def fused(params, toks0, kv_k, kv_v, bt):
        wk, wv = gather_window(kv_k, kv_v, bt, BS)

        def body(carry, j):
            t, rk, rv, rp = carry
            po = (pos + j)
            h, kn, vn = forward(params, mc, t, po, ones, wk, wv, win_len,
                                rk, rv, rp)
            lgt = logits_fn(params, mc, h[:, 0])
            nxt = sample_tokens(lgt, temps, tk, tp, seeds)
            rk = jax.lax.dynamic_update_slice(rk, kn, (0, 0, 0, j, 0))
            rv = jax.lax.dynamic_update_slice(rv, vn, (0, 0, 0, j, 0))
            rp = jax.lax.dynamic_update_slice(rp, po, (0, j))
            return (nxt[:, None].astype(jnp.int32), rk, rv, rp), nxt

        (_, rk, rv, _), out = jax.lax.scan(
            body, (toks0, ring_k, ring_v, ring_pos),
            jnp.arange(K, dtype=jnp.int32))
        return out, rk, rv

    fj = jax.jit(fused)
    ms, _ = timed(fj, params, toks, kv_k, kv_v, bt, n=5)
    print(f"fused K={K}:    {ms:8.2f} ms  -> {ms/K:.2f} ms/step "
          f"-> {B*K/(ms/1e3):.0f} tok/s")

    # 6. forward WITHOUT window (weights only ceiling)
    fwd0 = jax.jit(lambda p, t, po, rk, rv, rp: forward(
        p, mc, t, po, ones, None, None, None, rk, rv, rp))
    ms, _ = timed(fwd0, params, toks, pos, ring_k, ring_v, ring_pos)
    print(f"forward-nowin: {ms:8.2f} ms")

    with jax.profiler.trace("/tmp/jax-trace"):
        out = fj(params, toks, kv_k, kv_v, bt)
        jax.block_until_ready(out)
    print("trace written to /tmp/jax-trace")


if __name__ == "__main__":
    main()
