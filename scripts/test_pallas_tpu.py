"""Smoke the Pallas flash-decode kernel on the real TPU and compare to XLA.
Run: PYTHONPATH=/root/.axon_site:/root/repo python scripts/test_pallas_tpu.py
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.ops.attention import paged_attention_xla
from production_stack_tpu.ops.pallas.paged_attention import (
    paged_attention_decode_pallas,
)


def trial(dh, hkv=8, g=4, b=16, s=1024, bs=16, dtype=jnp.bfloat16):
    h = hkv * g
    nslots = b * s + bs
    mb = s // bs
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (b, 1, h, dh), dtype)
    kp = jax.random.normal(k2, (hkv, nslots, dh), dtype)
    vp = jax.random.normal(k3, (hkv, nslots, dh), dtype)
    bt = np.zeros((b, mb), np.int32)
    for i in range(b):
        bt[i] = np.arange(1 + i * mb, 1 + (i + 1) * mb)
    bt = jnp.asarray(bt)
    lens = jnp.full((b,), s, jnp.int32)
    pos = jnp.full((b, 1), s - 1, jnp.int32)

    ref = paged_attention_xla(q, kp, vp, bt, lens, pos, block_size=bs)
    try:
        out = paged_attention_decode_pallas(q, kp, vp, bt, lens, block_size=bs)
        out.block_until_ready()
    except Exception as e:  # noqa: BLE001
        print(f"dh={dh}: PALLAS FAILED: {type(e).__name__}: {str(e)[:300]}")
        return
    err = jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))
    # timing
    for fn, name in ((paged_attention_decode_pallas, "pallas"),):
        t0 = time.perf_counter()
        for _ in range(20):
            o = fn(q, kp, vp, bt, lens, block_size=bs)
        o.block_until_ready()
        ms = (time.perf_counter() - t0) / 20 * 1000
        kvb = 2 * hkv * b * s * dh * 2
        print(f"dh={dh} {name}: max_err={float(err):.4f} {ms:.2f} ms "
              f"({kvb/ms*1e3/2**30:.0f} GiB/s)")
    t0 = time.perf_counter()
    for _ in range(20):
        o = paged_attention_xla(q, kp, vp, bt, lens, pos, block_size=bs)
    o.block_until_ready()
    ms = (time.perf_counter() - t0) / 20 * 1000
    print(f"dh={dh} xla-gather: {ms:.2f} ms")


if __name__ == "__main__":
    trial(128)
    trial(64)
